"""Serialization: cloudpickle + pickle-5 out-of-band buffers for zero-copy payloads.

Counterpart of the reference's python/ray/_private/serialization.py + vendored
cloudpickle (python/ray/cloudpickle/) + plasma zero-copy numpy reads.  A value is
serialized to ``SerializedObject(inband, buffers)``: the in-band pickle stream plus a
flat list of large contiguous buffers (numpy arrays, jax host arrays, bytes) captured
via the protocol-5 ``buffer_callback``.  Buffers are written verbatim into the
shared-memory store and mapped back as memoryviews on read, so a worker-to-worker
transfer of a numpy array copies it at most once (into shm) per node.

ObjectRefs found inside values are recorded so the owner can track borrowers
(reference: reference_count.h:61 borrower protocol; simplified here).
"""

from __future__ import annotations

import io
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

# Values >= this many bytes are moved out-of-band; tiny buffers stay in-band to
# avoid per-buffer bookkeeping overhead.
_OOB_THRESHOLD = 4096


class SerializedObject:
    __slots__ = ("inband", "buffers", "contained_refs")

    def __init__(self, inband: bytes, buffers: List[memoryview], contained_refs=None):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs or []

    def total_bytes(self) -> int:
        return len(self.inband) + sum(b.nbytes for b in self.buffers)

    def total_frame_bytes(self) -> int:
        """Size of the flattened frame (header + segments)."""
        return 12 + len(self.inband) + sum(8 + b.nbytes for b in self.buffers)

    def write_into(self, dest) -> int:
        """Write the flattened frame into a writable buffer (e.g. a mapped
        plasma segment) without materializing an intermediate copy; returns
        bytes written.  Layout: [n_bufs][len inband][inband][len buf][buf]..."""
        mv = memoryview(dest)
        mv[0:4] = len(self.buffers).to_bytes(4, "little")
        mv[4:12] = len(self.inband).to_bytes(8, "little")
        off = 12
        mv[off:off + len(self.inband)] = self.inband
        off += len(self.inband)
        for b in self.buffers:
            mv[off:off + 8] = b.nbytes.to_bytes(8, "little")
            off += 8
            flat = b if b.ndim == 1 and b.format == "B" else b.cast("B")
            mv[off:off + flat.nbytes] = flat
            off += flat.nbytes
        return off

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous frame (small objects / wire fallback)."""
        out = bytearray(self.total_frame_bytes())
        self.write_into(out)
        return bytes(out)

    def iter_frame(self, chunk_bytes: int):
        """Yield the flattened frame as a sequence of chunks, each at most
        ``chunk_bytes``, WITHOUT materializing the whole frame: large
        buffers are sliced in place, only sub-chunk header/length pieces are
        stitched together.  Streaming consumers (a ray:// driver shipping a
        multi-GiB put over RPC) stay at one-chunk peak memory instead of
        2x the payload."""
        assert chunk_bytes > 0
        pending = bytearray()

        def pieces():
            yield len(self.buffers).to_bytes(4, "little")
            yield len(self.inband).to_bytes(8, "little")
            yield self.inband
            for b in self.buffers:
                yield b.nbytes.to_bytes(8, "little")
                flat = b if getattr(b, "ndim", 1) == 1 and \
                    getattr(b, "format", "B") == "B" else b.cast("B")
                yield flat

        for piece in pieces():
            mv = memoryview(piece) if not isinstance(piece, memoryview) \
                else piece
            off = 0
            while off < mv.nbytes:
                take = min(chunk_bytes - len(pending), mv.nbytes - off)
                if not pending and take == chunk_bytes:
                    # full chunk straight out of the source: zero-copy slice
                    yield mv[off:off + take]
                else:
                    pending.extend(mv[off:off + take])
                    if len(pending) == chunk_bytes:
                        # Swap instead of copy: the filled bytearray is
                        # yielded as-is and a fresh one accumulates the next
                        # tail, so each stitched chunk costs exactly the one
                        # extend() copy.
                        out, pending = pending, bytearray()
                        yield memoryview(out)
                off += take
        if pending:
            yield memoryview(pending)

    @classmethod
    def from_buffer(cls, buf) -> "SerializedObject":
        """Parse a flattened frame, keeping buffers as zero-copy memoryviews."""
        mv = memoryview(buf)
        n_bufs = int.from_bytes(mv[:4], "little")
        inband_len = int.from_bytes(mv[4:12], "little")
        off = 12
        inband = bytes(mv[off : off + inband_len])
        off += inband_len
        buffers = []
        for _ in range(n_bufs):
            blen = int.from_bytes(mv[off : off + 8], "little")
            off += 8
            buffers.append(mv[off : off + blen])
            off += blen
        return cls(inband, buffers)


def freeze_buffers(buffers) -> Tuple[List[Any], int]:
    """Prepare OOB buffers for an in-flight frame (inline args, packed
    returns): readonly views pass through zero-copy as ``PickleBuffer``s
    (protocol-5 picklable; the RPC encoder's buffer_callback ships them
    out-of-band, so they never flatten); writable views are copied,
    because the owner can mutate the backing array between submission and
    the asynchronous wire write.  Returns (buffers, n_copied) so callers
    can count residual copies."""
    out: List[Any] = []
    copied = 0
    for b in buffers:
        mv = b if isinstance(b, memoryview) else memoryview(b)
        if mv.readonly:
            out.append(pickle.PickleBuffer(mv))
        else:
            out.append(bytes(mv))
            copied += 1
    return out, copied


class SerializationContext:
    """Per-process serializer with a custom-reducer registry.

    Reference: python/ray/util/serialization.py register_serializer and
    _private/serialization.py SerializationContext.
    """

    def __init__(self):
        self._custom: Dict[type, Tuple[Callable, Callable]] = {}
        self._lock = threading.Lock()
        self._jax_registered = False
        self._pickler_cls = None

    def register_serializer(self, cls: type, serializer: Callable, deserializer: Callable):
        with self._lock:
            self._custom[cls] = (serializer, deserializer)

    def deregister_serializer(self, cls: type):
        with self._lock:
            self._custom.pop(cls, None)

    def _make_pickler(self, file, buffer_callback):
        # Cache the Pickler subclass: creating a class per serialize() call
        # costs more than the pickling itself for small hot-path messages
        # (compiled-DAG channel frames).  The closure captures the _custom
        # dict by reference, so later register_serializer calls are seen.
        cls = self._pickler_cls
        if cls is None:
            custom = self._custom

            class _Pickler(cloudpickle.Pickler):
                def reducer_override(self, obj):  # noqa: N802
                    entry = custom.get(type(obj))
                    if entry is None:
                        for base in type(obj).__mro__[1:]:
                            entry = custom.get(base)
                            if entry is not None:
                                break
                    if entry is not None:
                        serializer, deserializer = entry
                        return (_apply_deserializer,
                                (deserializer, serializer(obj)))
                    # Chain to cloudpickle's own reducer_override (it handles
                    # functions/classes by value) rather than disabling it.
                    return super().reducer_override(obj)

            cls = self._pickler_cls = _Pickler

        return cls(file, protocol=5, buffer_callback=buffer_callback)

    def serialize(self, value: Any) -> SerializedObject:
        # Fast path: scalar-ish builtins cannot contain ObjectRefs, OOB
        # buffers, or custom-reduced objects — plain pickle, no cloudpickle
        # Pickler construction (this is the compiled-DAG per-message path).
        t = type(value)
        if t in _FAST_TYPES and t not in self._custom:
            return SerializedObject(  # scalars: no buffers exist to flatten
                pickle.dumps(value, protocol=5), [])  # lint: disable=no-flatten
        if not self._jax_registered:
            import sys

            if "jax" in sys.modules:
                self._jax_registered = True
                maybe_register_jax(self)
        buffers: List[memoryview] = []
        contained_refs: List[Any] = []

        def buffer_callback(pickle_buffer: pickle.PickleBuffer) -> bool:
            mv = pickle_buffer.raw()
            if mv.nbytes < _OOB_THRESHOLD:
                return True  # keep in-band
            buffers.append(mv)
            return False

        _CONTAINED_REFS_TLS.stack.append(contained_refs)
        try:
            f = io.BytesIO()
            pickler = self._make_pickler(f, buffer_callback)
            pickler.dump(value)
            inband = f.getvalue()
        finally:
            _CONTAINED_REFS_TLS.stack.pop()
        return SerializedObject(inband, buffers, contained_refs)

    def deserialize(self, serialized: SerializedObject) -> Any:
        return pickle.loads(serialized.inband, buffers=serialized.buffers)


_FAST_TYPES = (int, float, bool, type(None), str)


def _apply_deserializer(deserializer, payload):
    return deserializer(payload)


class _ContainedRefsTLS(threading.local):
    def __init__(self):
        self.stack: List[List[Any]] = []


_CONTAINED_REFS_TLS = _ContainedRefsTLS()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ while a serialize() is in flight."""
    if _CONTAINED_REFS_TLS.stack:
        _CONTAINED_REFS_TLS.stack[-1].append(ref)


_default_context: Optional[SerializationContext] = None
_default_lock = threading.Lock()


def get_serialization_context() -> SerializationContext:
    global _default_context
    ctx = _default_context
    if ctx is None:
        with _default_lock:
            ctx = _default_context
            if ctx is None:
                ctx = _default_context = SerializationContext()
    return ctx


def maybe_register_jax(ctx: Optional[SerializationContext] = None) -> None:
    """Register the jax.Array host-copy serializer.

    MUST NOT create any jax array or query devices: that would initialize a
    backend (on TPU VMs the runtime client), blocking workers that merely have
    jax imported.  ``jax.Array`` is an ABC in every concrete array's MRO, which
    is exactly what the reducer_override lookup walks.
    """
    import sys

    if "jax" not in sys.modules:
        return
    ctx = ctx or get_serialization_context()
    import jax
    import numpy as np

    def _ser_jax(arr):
        # device_get already returns a numpy array for host-backed arrays;
        # asarray on top of that would be a redundant full copy.  Only
        # materialize when needed, and keep the result C-contiguous so the
        # pickle-5 buffer_callback can take it out-of-band.
        out = jax.device_get(arr)
        if not isinstance(out, np.ndarray):
            out = np.asarray(out)
        if not out.flags.c_contiguous:
            out = np.ascontiguousarray(out)
        return out

    def _deser_jax(np_arr):
        return np_arr

    ctx.register_serializer(jax.Array, _ser_jax, _deser_jax)
