"""Shared-memory object store: the plasma equivalent.

Counterpart of the reference's plasma store (reference: src/ray/object_manager/plasma/
store.h:55, object_lifecycle_manager.h:101, eviction_policy.h, plasma_allocator.cc) and
the client side (src/ray/core_worker/store_provider/plasma_store_provider.h:88).

Design, TPU-host-native rather than a translation:

- One store per node, hosted inside the nodelet (raylet-equivalent) process.  Objects
  live in POSIX shared memory (``/dev/shm`` via ``multiprocessing.shared_memory``),
  one segment per object.  The reference instead dlmalloc's one big mmap arena and
  passes fds (plasma/fling.cc); per-object segments let clients attach by *name* over
  the normal RPC channel — no fd-passing — at the cost of one ``memfd`` per object,
  which is fine at the object counts a training cluster sees and removes the whole
  allocator (XLA owns device memory; host shm is a staging area).
- Zero-copy reads: clients map the segment and deserialize with pickle-5 buffers
  pointing straight into it (numpy arrays alias shm).  The mapping outlives deletion:
  POSIX keeps unlinked segments alive until the last mapping closes, which is exactly
  the pin-until-last-view semantics plasma implements with refcounts.
- Eviction & spilling: sealed, unpinned objects are spilled to disk (primary copies)
  or evicted (remote copies) in LRU order when a create needs room (reference:
  eviction_policy.h + local_object_manager.h:41 spill path, simplified into one
  component).  Restore happens transparently inside ``get``.
- Admission: creates larger than free capacity + evictable bytes raise
  ``ObjectStoreFullError`` after retrying, like the CreateRequestQueue
  (plasma/create_request_queue.h).

Server-side methods are synchronous and only called from the nodelet's event loop
(single-threaded, like the reference store's single io_context thread).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import pickle
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private import fault_injection
from ray_tpu._private.config import RayConfig
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

logger = logging.getLogger(__name__)


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment without registering with resource_tracker.

    The tracker would try to unlink segments owned by the store when *this*
    process exits; only the store unlinks.
    """
    shm = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    orig_close = shm.close

    def _close_tolerant():
        try:
            orig_close()
        except BufferError:
            # A zero-copy numpy view still aliases the mapping (interpreter
            # shutdown / GC); the segment is store-owned, leaking the mapping
            # until process exit matches plasma's pin semantics.
            pass

    shm.close = _close_tolerant
    return shm


class _Entry:
    __slots__ = (
        "oid", "shm", "size", "alloc", "sealed", "pins", "last_access",
        "is_primary", "spilled_path", "ever_viewed", "slab", "offset",
    )

    def __init__(self, oid: ObjectID, shm: Optional[shared_memory.SharedMemory], size: int, is_primary: bool,
                 alloc: Optional[int] = None):
        self.oid = oid
        self.shm = shm
        self.size = size
        self.alloc = alloc if alloc is not None else size  # segment bytes
        self.sealed = False
        self.pins = 0  # outstanding client pins; only 0-pin objects evict
        self.last_access = time.monotonic()
        self.is_primary = is_primary  # created locally by owner (vs pulled copy)
        self.spilled_path: Optional[str] = None
        # True once ANY reader (client mapping or server-side view) may have
        # aliased the segment.  Such segments must be unlinked, never pooled:
        # a lingering zero-copy view must keep seeing the old bytes (plasma's
        # pin-until-last-view contract).
        self.ever_viewed = False
        # Arena-backed entries: the payload lives at slab[offset:offset+size]
        # of a shared slab instead of its own segment (shm stays None).
        self.slab: Optional[str] = None
        self.offset: int = 0


# Extent alignment inside arena slabs: page granularity keeps every object
# frame page-aligned (clean zero-copy numpy views) at <4% overhead for the
# >=100 KiB objects plasma holds.
_EXTENT_ALIGN = 4096


def _align(n: int) -> int:
    return (n + _EXTENT_ALIGN - 1) & ~(_EXTENT_ALIGN - 1)


def _is_slab_name(name: str) -> bool:
    """Slab segment names end in an 'a'-prefixed sequence component (see
    PlasmaStore._slab_name); per-object segments use a bare number."""
    return name.rsplit("_", 1)[-1].startswith("a")


class _Slab:
    """One pre-faulted arena segment with a sorted, coalesced free list.

    The reference's plasma store dlmalloc's a single pre-mapped arena so a
    put never pays first-touch page faults (plasma_allocator.cc); these
    slabs are the same idea sized to stay poolable: pages are touched once
    at slab creation, and every later extent allocation writes at memcpy
    speed."""

    __slots__ = ("name", "shm", "size", "free")

    def __init__(self, name: str, shm: shared_memory.SharedMemory, size: int):
        self.name = name
        self.shm = shm
        self.size = size
        self.free: List[List[int]] = [[0, size]]  # sorted [off, len] runs

    def free_bytes(self) -> int:
        return sum(ln for _off, ln in self.free)

    def alloc(self, size: int) -> Optional[int]:
        """First-fit extent allocation; returns offset or None."""
        size = _align(size)
        for i, (off, ln) in enumerate(self.free):
            if ln >= size:
                if ln == size:
                    self.free.pop(i)
                else:
                    self.free[i] = [off + size, ln - size]
                return off
        return None

    def release(self, off: int, size: int) -> None:
        """Return [off, off+size) to the free list, merging neighbors."""
        size = _align(size)
        import bisect

        i = bisect.bisect_left(self.free, [off, 0])
        self.free.insert(i, [off, size])
        # merge with successor then predecessor
        if i + 1 < len(self.free) and \
                self.free[i][0] + self.free[i][1] == self.free[i + 1][0]:
            self.free[i][1] += self.free[i + 1][1]
            self.free.pop(i + 1)
        if i > 0 and self.free[i - 1][0] + self.free[i - 1][1] == \
                self.free[i][0]:
            self.free[i - 1][1] += self.free[i][1]
            self.free.pop(i)


class PlasmaStore:
    """Node-local shared-memory store. All methods run on the nodelet loop."""

    def __init__(self, capacity_bytes: int, spill_dir: Optional[str] = None, node_id_hex: str = ""):
        self.capacity = capacity_bytes
        self.used = 0
        self.objects: Dict[ObjectID, _Entry] = {}
        self.spill_dir = spill_dir
        self.node_id_hex = node_id_hex
        self._seq = 0
        # Callbacks wired by the nodelet: object sealed / deleted locally
        # (feeds the GCS object directory, reference: ownership_based_object_directory.h).
        self.on_sealed = None
        self.on_deleted = None
        self.num_spilled = 0
        self.bytes_spilled = 0
        # Segment pool: freed never-viewed segments keyed by allocation
        # bucket, kept MAPPED so their pages stay physically allocated.  A
        # fresh 64 MiB segment costs ~90 ms of first-touch page faults on
        # write; a pooled one writes at memcpy speed.  This is the per-object
        #-segment equivalent of the reference's one-arena dlmalloc design
        # (plasma/plasma_allocator.cc), where pages are faulted once per
        # store lifetime.
        self._seg_pool: Dict[int, List[shared_memory.SharedMemory]] = {}
        self._pool_bytes = 0
        self._pool_cap = min(256 * 1024 * 1024, capacity_bytes // 4)
        # Arena: pre-faulted slabs carved into extents.  Slab bytes count
        # against capacity at creation (they are committed memory); live
        # objects, leased extents, and free runs all live inside them.
        self.slabs: Dict[str, _Slab] = {}
        # deleted-but-still-pinned arena entries: the extent is reusable
        # only after the last reader releases (a shared slab has no POSIX
        # unlink safety net — reuse under a live zero-copy view corrupts it)
        self._zombies: Dict[ObjectID, _Entry] = {}

    # Segments below this aren't pooled: their first-touch cost is trivial
    # and page-rounding would distort small-capacity accounting.
    _POOL_MIN_SEGMENT = 1024 * 1024

    @classmethod
    def _bucket(cls, size: int) -> int:
        """Round poolable allocations to whole pages; repeated puts of
        same-shaped payloads (the common steady-state) then land in matching
        buckets."""
        if size < cls._POOL_MIN_SEGMENT:
            return max(size, 1)
        return (size + 4095) & ~4095

    def _pool_take(self, bucket: int) -> Optional[shared_memory.SharedMemory]:
        pool = self._seg_pool.get(bucket)
        if pool:
            self._pool_bytes -= bucket
            return pool.pop()
        return None

    def _pool_reclaim(self, need: int) -> None:
        """Unlink pooled segments (largest first) to free real memory."""
        freed = 0
        for bucket in sorted(self._seg_pool, reverse=True):
            pool = self._seg_pool[bucket]
            while pool and freed < need:
                shm = pool.pop()
                self._pool_bytes -= bucket
                freed += bucket
                try:
                    shm.unlink()
                except FileNotFoundError:
                    pass
                shm.close()
            if freed >= need:
                break

    # -- helpers -------------------------------------------------------------
    def _segment_name(self) -> str:
        self._seq += 1
        return f"rtpu_{self.node_id_hex[:8]}_{os.getpid()}_{self._seq}"

    def _slab_name(self) -> str:
        self._seq += 1
        return f"rtpu_{self.node_id_hex[:8]}_{os.getpid()}_a{self._seq}"

    # ------------------------------------------------------------- arena
    def _new_slab(self, min_bytes: int) -> Optional[_Slab]:
        """Create a pre-faulted slab of at least min_bytes (rounded up to
        the configured slab size); returns None when capacity can't fit it
        even after eviction."""
        size = max(_align(min_bytes), RayConfig.arena_slab_bytes)
        if not self._ensure_room(size):
            # a smaller slab may still fit when the request itself is small
            if size > _align(min_bytes):
                size = _align(min_bytes)
                if not self._ensure_room(size):
                    return None
            else:
                return None
        shm = shared_memory.SharedMemory(
            name=self._slab_name(), create=True, size=size)
        # First-touch every page NOW, off the put hot path: a fresh 64 MiB
        # mapping costs tens of ms of page faults on first write; a
        # pre-faulted slab takes puts at memcpy speed for its whole life.
        buf = shm.buf
        zero = b"\0" * (1 << 20)
        for off in range(0, size, 1 << 20):
            n = min(1 << 20, size - off)
            buf[off:off + n] = zero[:n]
        slab = _Slab(shm.name, shm, size)
        self.slabs[shm.name] = slab
        self.used += size
        return slab

    def _arena_find(self, size: int) -> Optional[Tuple[str, int]]:
        """First-fit extent from existing slabs (no eviction, no new slab)."""
        for slab in self.slabs.values():
            off = slab.alloc(size)
            if off is not None:
                return slab.name, off
        return None

    def _arena_victims(self) -> List[_Entry]:
        return sorted((e for e in self.objects.values()
                       if e.sealed and e.pins == 0 and e.slab is not None),
                      key=lambda e: e.last_access)

    def lease_extents(self, nbytes: int, contig: int) -> List[Tuple[str, int, int]]:
        """Grant extents totaling ~nbytes, the first at least ``contig``
        contiguous bytes.  Evicts LRU arena objects, then creates a new
        slab, before giving up with ObjectStoreFullError.  Only the contig
        minimum forces eviction; the top-up is opportunistic."""
        contig = _align(max(contig, 1))
        if contig > self.capacity:
            raise ObjectStoreFullError(
                f"extent of {contig} bytes exceeds store capacity "
                f"{self.capacity}")
        got = self._arena_find(contig)
        if got is None:
            # Grow the arena while capacity is plentiful — eviction/spill is
            # strictly worse than committing free capacity to another
            # pre-faulted slab.  Fully-free slabs that survive to this point
            # are the WRONG SIZE for contig (else _arena_find would have
            # used them): reclaim them before deciding capacity is short —
            # a pile of stale 64 MiB slabs must not force spilling a live
            # 256 MiB object (observed: workload shifting put sizes).
            slab_need = max(_align(contig), RayConfig.arena_slab_bytes)
            need = self.used + self._pool_bytes + slab_need - self.capacity
            if need > 0:
                self._reclaim_arena(need)
            if self.used + self._pool_bytes + slab_need <= self.capacity or \
                    self.used + self._pool_bytes + _align(contig) <= self.capacity:
                slab = self._new_slab(contig)
                if slab is not None:
                    got = (slab.name, slab.alloc(contig))
        if got is None:
            # Capacity-bound: evict LRU arena objects until a contiguous
            # extent frees up.
            for victim in self._arena_victims():
                if victim.is_primary:
                    if not self.spill_dir:
                        continue  # sole copy: never dropped to make room
                    self._spill(victim)
                else:
                    self._drop_entry_storage(victim)
                    if not victim.spilled_path:
                        del self.objects[victim.oid]
                        if self.on_deleted:
                            self.on_deleted(victim.oid)
                got = self._arena_find(contig)
                if got is not None:
                    break
        if got is None:
            # Last resort: a fresh slab carved out of whatever _ensure_room
            # can still reclaim (segment pool, legacy evictions).
            slab = self._new_slab(contig)
            if slab is not None:
                got = (slab.name, slab.alloc(contig))
        if got is None or got[1] is None:
            raise ObjectStoreFullError(
                f"store full: need a {contig}-byte extent, used "
                f"{self.used}/{self.capacity}, arena free "
                f"{self.arena_free_bytes()}")
        extents = [(got[0], got[1], contig)]
        granted = contig
        want = _align(max(nbytes, contig))
        while granted < want and len(extents) < 8:
            more = self._arena_find(min(_align(want - granted), contig))
            if more is None:
                # Top-up stays strictly opportunistic: free extents in
                # existing slabs only.  Creating slabs here was measured
                # SLOWER on a put storm — each new slab pays a full
                # pre-fault zeroing pass, which costs more than the lease
                # RPC it saves (and at the capacity line the grow path
                # starts unlinking/recreating pre-faulted slabs, churning).
                break
            take = min(_align(want - granted), contig)
            extents.append((more[0], more[1], take))
            granted += take
        return extents

    def free_extent(self, slab_name: str, off: int, length: int) -> None:
        slab = self.slabs.get(slab_name)
        if slab is None:
            return
        slab.release(off, length)

    def seal_extent(self, oid: ObjectID, slab_name: str, off: int,
                    size: int, alen: int, is_primary: bool = True) -> bool:
        """Register + seal an object a client wrote into its leased extent —
        the fused put/seal (no create round trip, no separate seal).
        Returns False (and frees the extent) on a duplicate oid."""
        if slab_name not in self.slabs:
            logger.warning("seal for unknown slab %s (oid %s)", slab_name,
                           oid.hex()[:16])
            return False
        if oid in self.objects:
            self.free_extent(slab_name, off, alen)
            return False
        e = _Entry(oid, None, size, is_primary, alloc=_align(alen))
        e.slab = slab_name
        e.offset = off
        e.sealed = True
        self.objects[oid] = e
        if self.on_sealed:
            self.on_sealed(oid, size)
        return True

    def arena_free_bytes(self) -> int:
        return sum(s.free_bytes() for s in self.slabs.values())

    def _reclaim_arena(self, need: int) -> int:
        """Unlink fully-free slabs to give bytes back to `used` capacity."""
        freed = 0
        for name in list(self.slabs):
            if freed >= need:
                break
            slab = self.slabs[name]
            if slab.free_bytes() == slab.size:
                del self.slabs[name]
                self.used -= slab.size
                freed += slab.size
                try:
                    slab.shm.unlink()
                except FileNotFoundError:
                    pass
                try:
                    slab.shm.close()
                except BufferError:
                    pass  # a transient server-side view; pages die with it
        return freed

    def _drop_entry_storage(self, e: _Entry) -> None:
        """Release an entry's backing bytes (arena extent or segment)."""
        if e.slab is not None:
            self.free_extent(e.slab, e.offset, e.alloc)
            e.slab = None
        else:
            self._drop_shm(e)

    def _evictable(self) -> List[_Entry]:
        return [
            e for e in self.objects.values()
            if e.sealed and e.pins == 0
            and (e.shm is not None or e.slab is not None)
        ]

    def _ensure_room(self, size: int) -> bool:
        if self.used + self._pool_bytes + size <= self.capacity:
            return True
        # Pooled (free but still-mapped) segments are the cheapest room,
        # then fully-free arena slabs (same idea at slab granularity).
        need = self.used + self._pool_bytes + size - self.capacity
        self._pool_reclaim(need)
        self._reclaim_arena(self.used + self._pool_bytes + size - self.capacity)
        if self.used + self._pool_bytes + size <= self.capacity:
            return True
        victims = sorted(self._evictable(), key=lambda e: e.last_access)
        for e in victims:
            if self.used + self._pool_bytes + size <= self.capacity:
                break
            if e.is_primary:
                if self.spill_dir:
                    self._spill(e)
                # No spill dir: a primary copy is the ONLY copy — never delete
                # it to make room; the create fails instead.
            else:
                # pool_ok=False: this eviction exists to FREE memory — moving
                # the segment into the pool would make no progress and spill
                # further victims for nothing.
                if e.slab is not None:
                    self._drop_entry_storage(e)
                else:
                    self._drop_shm(e, pool_ok=False)
                if not e.spilled_path:
                    del self.objects[e.oid]
                    if self.on_deleted:
                        self.on_deleted(e.oid)
            # evicted arena extents only become reclaimable capacity once
            # their slab is fully free — sweep as we go
            self._reclaim_arena(
                self.used + self._pool_bytes + size - self.capacity)
        self._pool_reclaim(self.used + self._pool_bytes + size - self.capacity)
        return self.used + self._pool_bytes + size <= self.capacity

    def _spill(self, e: _Entry) -> None:
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, e.oid.hex())
        if e.slab is not None:
            src = self.slabs[e.slab].shm.buf[e.offset:e.offset + e.size]
        else:
            src = e.shm.buf[: e.size]
        with open(path, "wb") as f:
            f.write(src)
        del src
        e.spilled_path = path
        self.num_spilled += 1
        self.bytes_spilled += e.size
        # spilling exists to free memory: bypass the pool
        if e.slab is not None:
            self._drop_entry_storage(e)
        else:
            self._drop_shm(e, pool_ok=False)

    def _restore(self, e: _Entry) -> None:
        # Arena first: a restored extent lands in pre-faulted pages (and a
        # restored object may well be read again soon).
        got = self._arena_find(_align(e.size))
        if got is not None:
            slab_name, off = got
            with open(e.spilled_path, "rb") as f:
                f.readinto(self.slabs[slab_name].shm.buf[off:off + e.size])
            e.slab = slab_name
            e.offset = off
            e.alloc = _align(e.size)
            e.ever_viewed = False
            return
        alloc = self._bucket(e.size)
        shm = self._pool_take(alloc)
        if shm is None:
            if not self._ensure_room(alloc):
                raise ObjectStoreFullError(
                    f"cannot restore {e.oid}: store full ({self.used}/{self.capacity})"
                )
            shm = shared_memory.SharedMemory(
                name=self._segment_name(), create=True, size=alloc)
        with open(e.spilled_path, "rb") as f:
            f.readinto(shm.buf)
        e.shm = shm
        e.alloc = alloc
        e.ever_viewed = False
        self.used += alloc

    def _drop_shm(self, e: _Entry, pool_ok: bool = True) -> None:
        if e.shm is not None:
            self.used -= e.alloc
            if pool_ok and not e.ever_viewed and \
                    e.alloc >= self._POOL_MIN_SEGMENT and \
                    self._pool_bytes + e.alloc <= self._pool_cap:
                # Never aliased by a reader: safe to recycle with pages hot.
                self._seg_pool.setdefault(e.alloc, []).append(e.shm)
                self._pool_bytes += e.alloc
            else:
                try:
                    e.shm.unlink()
                except FileNotFoundError:
                    pass
                try:
                    e.shm.close()
                except BufferError:
                    # A transient server-side view (push/spill in flight)
                    # still aliases the buffer; the segment is unlinked so
                    # the pages are reclaimed when the mapping dies with the
                    # view.
                    pass
            e.shm = None

    # -- API -----------------------------------------------------------------
    def create(self, oid: ObjectID, size: int, is_primary: bool = True) -> str:
        """Allocate a segment for oid; returns the shm name for the client to map."""
        if oid in self.objects:
            e = self.objects[oid]
            if e.sealed:
                raise FileExistsError(f"object {oid} already sealed")
            # Re-create (e.g. failed writer): drop the half-written segment.
            self._drop_shm(e)
            del self.objects[oid]
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity {self.capacity}"
            )
        alloc = self._bucket(size)
        shm = self._pool_take(alloc)
        if shm is None:
            if not self._ensure_room(alloc):
                raise ObjectStoreFullError(
                    f"store full: need {size}, used {self.used}/{self.capacity}, "
                    f"evictable {sum(x.size for x in self._evictable())}"
                )
            shm = shared_memory.SharedMemory(
                name=self._segment_name(), create=True, size=alloc)
        e = _Entry(oid, shm, size, is_primary, alloc=alloc)
        self.objects[oid] = e
        self.used += alloc
        return shm.name

    def seal(self, oid: ObjectID) -> None:
        e = self.objects[oid]
        e.sealed = True
        e.last_access = time.monotonic()
        if self.on_sealed:
            self.on_sealed(oid, e.size)

    def abort(self, oid: ObjectID) -> None:
        """Drop an unsealed (half-written) entry, e.g. a failed chunked pull."""
        e = self.objects.get(oid)
        if e is not None and not e.sealed:
            self._drop_shm(e)
            del self.objects[oid]

    def write_buffer(self, oid: ObjectID):
        """Writable view of an unsealed entry (chunked transfer landing pad)."""
        e = self.objects[oid]
        assert not e.sealed, f"object {oid} already sealed"
        e.ever_viewed = True  # returned view may outlive the entry
        return e.shm.buf

    def write_and_seal(self, oid: ObjectID, data: memoryview, is_primary: bool = True) -> None:
        """Server-side path used by object transfer (pull) and spill restore."""
        if self.contains(oid):
            return
        name = self.create(oid, data.nbytes, is_primary=is_primary)
        e = self.objects[oid]
        e.shm.buf[: data.nbytes] = data
        del name
        self.seal(oid)

    def contains(self, oid: ObjectID) -> bool:
        e = self.objects.get(oid)
        return e is not None and e.sealed

    @staticmethod
    def _resident(e: _Entry) -> bool:
        return e.shm is not None or e.slab is not None

    def get_local(self, oid: ObjectID, pin: bool = True) -> Optional[Tuple[str, int, int]]:
        """Return (shm_name, size, offset) for a sealed local object,
        restoring from spill.  Arena objects resolve to their slab segment +
        offset; per-object segments report offset 0.  Pins the object so it
        survives until the client releases it."""
        e = self.objects.get(oid)
        if e is None or not e.sealed:
            return None
        if not self._resident(e) and e.spilled_path:
            self._restore(e)
        e.last_access = time.monotonic()
        e.ever_viewed = True  # client maps by name: segment can't be pooled
        if pin:
            e.pins += 1
        if e.slab is not None:
            return (e.slab, e.size, e.offset)
        return (e.shm.name, e.size, 0)

    def read_bytes(self, oid: ObjectID) -> Optional[memoryview]:
        """Server-side view of the object payload (for node-to-node push)."""
        e = self.objects.get(oid)
        if e is None or not e.sealed:
            return None
        if not self._resident(e) and e.spilled_path:
            self._restore(e)
        e.last_access = time.monotonic()
        if e.slab is not None:
            return self.slabs[e.slab].shm.buf[e.offset:e.offset + e.size]
        e.ever_viewed = True  # returned view may outlive the entry
        return e.shm.buf[: e.size]

    def release(self, oid: ObjectID) -> None:
        e = self.objects.get(oid)
        if e is not None:
            if e.pins > 0:
                e.pins -= 1
            return
        z = self._zombies.get(oid)
        if z is not None:
            z.pins -= 1
            if z.pins <= 0:
                # last reader of a deleted arena object: extent reusable now
                del self._zombies[oid]
                self._drop_entry_storage(z)

    def delete(self, oid: ObjectID) -> None:
        e = self.objects.pop(oid, None)
        if e is None:
            return
        if e.slab is not None and e.pins > 0:
            # a reader still maps the slab: extent reuse under its zero-copy
            # view would corrupt it (per-object segments get this for free
            # from POSIX unlink; shared slabs must defer explicitly)
            self._zombies[oid] = e
        else:
            self._drop_entry_storage(e)
        if e.spilled_path:
            try:
                os.remove(e.spilled_path)
            except OSError:
                pass
        if self.on_deleted:
            self.on_deleted(oid)

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "pooled": self._pool_bytes,
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "bytes_spilled": self.bytes_spilled,
            "arena_slabs": len(self.slabs),
            "arena_bytes": sum(s.size for s in self.slabs.values()),
            "arena_free": self.arena_free_bytes(),
            "zombie_extents": len(self._zombies),
        }

    def shutdown(self) -> None:
        for oid in list(self.objects):
            self.delete(oid)
        for oid in list(self._zombies):
            z = self._zombies.pop(oid)
            z.pins = 0
            self._drop_entry_storage(z)
        self._reclaim_arena(sum(s.size for s in self.slabs.values()))
        for slab in list(self.slabs.values()):  # extents still leased: force
            try:
                slab.shm.unlink()
            except FileNotFoundError:
                pass
            try:
                slab.shm.close()
            except BufferError:
                pass
            self.used -= slab.size
        self.slabs.clear()
        self._pool_reclaim(self._pool_bytes)


class PlasmaClient:
    """Client-side zero-copy access, used by CoreWorker.

    Methods are synchronous and called from the user thread; RPC metadata rides the
    worker's IO loop, the data path is direct shm mapping (reference:
    plasma_store_provider.h:88; zero-copy get semantics of plasma).

    The put hot path is round-trip-free in steady state: the client leases
    slab extents in bulk (one ``plasma_lease_extents`` RPC amortized over
    many puts), bump-allocates object frames inside them, and seals with a
    coalesced fire-and-forget notification — no ``plasma_create`` /
    ``plasma_seal`` round trips and no cold-page zeroing (slabs are
    pre-faulted server-side).
    """

    # Write-mapping cache budget: segment names recur when the store's pool
    # recycles a segment; re-attaching costs a full round of soft page
    # faults, so keeping the mapping makes repeated large puts run at
    # memcpy speed.  Names are never reused for a different segment (the
    # store's name sequence is monotonic), so a cached mapping is always
    # the right inode.  (Legacy path: arena puts write into slab mappings.)
    _WRITE_CACHE_BYTES = 256 * 1024 * 1024
    # A mapping of a segment the server has since unlinked can never hit
    # again (the name is gone forever) but still pins its pages outside the
    # store's accounting — drop any entry idle this long so stale mappings
    # are bounded in time, not only by budget pressure.
    _WRITE_CACHE_IDLE_S = 30.0

    def __init__(self, io, conn):
        # io: EventLoopThread, conn: Connection to the local nodelet
        self._io = io
        self._conn = conn
        # name -> [shm, in_use_count, last_used]; true LRU order (hits AND
        # releases refresh recency).  Guarded by _write_lock: puts run
        # concurrently on executor threads, and eviction must never close a
        # mapping another thread is mid-write on (in_use > 0).
        self._write_cache: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()
        self._write_cache_bytes = 0
        self._write_lock = threading.Lock()
        # Read-side mapping cache: one mapping per segment NAME (slabs are
        # shared by many objects and stay mapped; per-object segments close
        # when their object releases cleanly).
        self._maps: Dict[str, shared_memory.SharedMemory] = {}
        self._maps_lock = threading.Lock()
        # oid -> mapped segment name while we hold a server-side pin
        self._pins: Dict[ObjectID, str] = {}
        # oid -> memoryview slices handed to deserialization; a release may
        # only drop the server pin once every slice is releasable (an arena
        # extent must never be reused under a live zero-copy numpy view)
        self._views: Dict[ObjectID, list] = {}
        self._deferred_release: Set[ObjectID] = set()
        self._view_lock = threading.Lock()
        # Leased extent pool: [slab_name, off, len] carved by puts.
        self._extents: List[list] = []
        self._extent_lock = threading.Lock()
        self._extents_last_used = time.monotonic()
        self._extent_returns: List[Tuple[str, int, int]] = []
        # Adaptive prefetch: refills arriving back-to-back (a put storm)
        # double the next lease request, so the steady-state storm goes
        # RPC-free; the boost decays once the storm subsides.
        self._lease_boost = 1
        self._last_refill = 0.0
        # release coalescing: oids buffered here flush as ONE notify item
        self._release_buf: List[bytes] = []
        self._release_lock = threading.Lock()
        self._closed = False
        self._flush_task = io.spawn(self._flush_loop())

    # ------------------------------------------------------------ arena puts
    def put(self, oid: ObjectID, flat: memoryview | bytes) -> None:
        """Write + seal one object from an already-flat frame."""
        nbytes = flat.nbytes if isinstance(flat, memoryview) else len(flat)
        if not RayConfig.arena_enabled:
            return self._put_legacy(oid, flat, nbytes)
        slab, off = self._alloc_extent(nbytes)
        shm = self._map(slab)
        shm.buf[off:off + nbytes] = flat
        self._queue_seal(oid, slab, off, nbytes)

    def put_serialized(self, oid: ObjectID, ser) -> None:
        """Write + seal, streaming a SerializedObject's segments straight
        into the leased extent — no intermediate flat copy and, in steady
        state, no RPC round trip (bump-allocate + memcpy + coalesced seal
        notify)."""
        nbytes = ser.total_frame_bytes()
        if not RayConfig.arena_enabled:
            return self._put_serialized_legacy(oid, ser, nbytes)
        slab, off = self._alloc_extent(nbytes)
        shm = self._map(slab)
        ser.write_into(shm.buf[off:off + nbytes])
        self._queue_seal(oid, slab, off, nbytes)

    def _queue_seal(self, oid: ObjectID, slab: str, off: int,
                    nbytes: int) -> None:
        """Fire-and-forget fused seal: rides the per-tick coalesced batch
        frame.  A get racing ahead of the seal parks on the store's waiters
        and resolves when the seal lands (same-connection FIFO bounds the
        window to one tick)."""
        if fault_injection.ENABLED and fault_injection.hit(
                "plasma.seal", detail=oid.hex()) == "torn":
            # torn seal: the bytes were memcpy'd into the leased extent but
            # the store never learns the oid -- models a client SIGKILLed
            # in the window between write and seal notify
            return
        self._conn.notify_coalesced_threadsafe(
            "plasma_seal_extent",
            {"oid": oid.binary(), "slab": slab, "off": off,
             "size": nbytes, "alen": _align(nbytes)})

    def _alloc_extent(self, nbytes: int) -> Tuple[str, int]:
        """Carve an extent for one object from the local lease pool,
        refilling over RPC (with piggybacked extent returns) when dry."""
        alen = _align(nbytes)
        got = self._carve(alen)
        if got is not None:
            return got
        now = time.monotonic()
        if now - self._last_refill < 1.0:
            self._lease_boost = min(self._lease_boost * 2, 8)
        else:
            self._lease_boost = 1
        self._last_refill = now
        deadline = time.monotonic() + 30.0
        while True:
            with self._extent_lock:
                returns = self._extent_returns
                self._extent_returns = []
            msg = {"bytes": alen + max(alen * self._lease_boost,
                                       RayConfig.extent_lease_bytes),
                   "contig": alen,
                   "returns": [list(r) for r in returns]}
            try:
                resp = self._conn.call_sync("plasma_lease_extents", msg)
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                # hand back everything we hold before retrying: our own idle
                # lease may be the capacity the store is missing
                self.return_idle_extents(force=True)
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        with self._extent_lock:
            self._extents.extend([list(e) for e in resp["extents"]])
        got = self._carve(alen)
        assert got is not None, "lease grant lost between refill and carve"
        return got

    def _carve(self, alen: int) -> Optional[Tuple[str, int]]:
        with self._extent_lock:
            for i, ext in enumerate(self._extents):
                if ext[2] >= alen:
                    slab, off = ext[0], ext[1]
                    ext[1] += alen
                    ext[2] -= alen
                    if ext[2] <= 0:
                        self._extents.pop(i)
                    self._extents_last_used = time.monotonic()
                    return slab, off
        return None

    def return_idle_extents(self, force: bool = False) -> None:
        """Queue unused leased extents for return to the store.  Without
        ``force`` only extents idle past extent_lease_idle_s go back (the
        pool exists to keep steady-state puts RPC-free)."""
        now = time.monotonic()
        with self._extent_lock:
            if not force and \
                    now - self._extents_last_used < RayConfig.extent_lease_idle_s:
                return
            returns, self._extents = self._extents, []
            self._extent_returns.extend(
                (e[0], e[1], e[2]) for e in returns if e[2] > 0)
            pending = list(self._extent_returns)
            self._extent_returns = [] if pending else self._extent_returns
        if pending and not self._conn.closed:
            try:
                self._conn.notify_coalesced_threadsafe(
                    "plasma_return_extents",
                    {"extents": [list(p) for p in pending]})
            except ConnectionError:
                pass

    # ---------------------------------------------------------- legacy puts
    def _put_legacy(self, oid: ObjectID, flat, nbytes: int) -> None:
        got = self._create(oid, nbytes)
        if got is None:
            return
        name, shm, cached = got
        try:
            shm.buf[:nbytes] = flat
        finally:
            if cached:
                self._release_write(name)
            else:
                shm.close()
        self._conn.call_sync("plasma_seal", {"oid": oid.binary()})

    def _put_serialized_legacy(self, oid: ObjectID, ser, nbytes: int) -> None:
        got = self._create(oid, nbytes)
        if got is None:
            return
        name, shm, cached = got
        try:
            ser.write_into(shm.buf)
        finally:
            if cached:
                self._release_write(name)
            else:
                shm.close()
        self._conn.call_sync("plasma_seal", {"oid": oid.binary()})

    def _map_for_write(self, name: str) -> Tuple[shared_memory.SharedMemory, bool]:
        """Returns (mapping, cached).  Cached mappings must be released via
        _release_write (not closed); uncached ones are the caller's to
        close."""
        now = time.monotonic()
        with self._write_lock:
            # time-bounded pruning of idle mappings (see _WRITE_CACHE_IDLE_S)
            for k in [k for k, v in self._write_cache.items()
                      if v[1] == 0 and now - v[2] > self._WRITE_CACHE_IDLE_S]:
                old = self._write_cache.pop(k)
                self._write_cache_bytes -= old[0].size
                old[0].close()
            ent = self._write_cache.get(name)
            if ent is not None:
                ent[1] += 1
                ent[2] = now
                self._write_cache.move_to_end(name)
                return ent[0], True
        shm = _attach_shm(name)
        size = shm.size
        if size > self._WRITE_CACHE_BYTES:
            return shm, False
        with self._write_lock:
            if name in self._write_cache:  # raced with another thread
                ent = self._write_cache[name]
                ent[1] += 1
                ent[2] = now
                self._write_cache.move_to_end(name)
                to_close = shm
            else:
                self._evict_write_cache_locked(size)
                self._write_cache[name] = [shm, 1, now]
                self._write_cache_bytes += size
                return shm, True
        to_close.close()
        return ent[0], True

    def _evict_write_cache_locked(self, incoming: int) -> None:
        """Evict idle mappings in true LRU order until ``incoming`` fits.
        Busy entries (a concurrent put mid-write) are skipped in place; if
        everything is busy the cache briefly runs over budget."""
        if self._write_cache_bytes + incoming <= self._WRITE_CACHE_BYTES:
            return
        for victim in [k for k, v in self._write_cache.items() if v[1] == 0]:
            if self._write_cache_bytes + incoming <= self._WRITE_CACHE_BYTES:
                return
            old = self._write_cache.pop(victim)
            self._write_cache_bytes -= old[0].size
            old[0].close()

    def _release_write(self, name: str) -> None:
        with self._write_lock:
            ent = self._write_cache.get(name)
            if ent is not None:
                ent[1] = max(ent[1] - 1, 0)
                ent[2] = time.monotonic()
                # releases refresh recency too: a mapping written N times in
                # a row must not be the first evicted because its initial
                # insertion happens to be oldest
                self._write_cache.move_to_end(name)

    def _create(self, oid: ObjectID, nbytes: int):
        """Allocate a segment, waiting out transient store-full; returns the
        mapped shm or None if the object already exists."""
        deadline = time.monotonic() + 30.0
        while True:
            try:
                resp = self._conn.call_sync("plasma_create", {"oid": oid.binary(), "size": nbytes})
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        if resp.get("exists"):
            return None
        name = resp["name"]
        shm, cached = self._map_for_write(name)
        return name, shm, cached

    # ------------------------------------------------------------------ gets
    def _map(self, name: str) -> shared_memory.SharedMemory:
        with self._maps_lock:
            shm = self._maps.get(name)
            if shm is None:
                shm = _attach_shm(name)
                self._maps[name] = shm
            return shm

    def get_mapped(self, oid: ObjectID, timeout: Optional[float] = None) -> Optional[memoryview]:
        """Map a sealed object; returns a memoryview over shm or None on timeout.

        The nodelet blocks server-side until the object is local (pulling from
        remote nodes if needed), so no client-side polling.
        """
        resp = self._conn.call_sync(
            "plasma_get", {"oid": oid.binary(), "timeout": timeout}, timeout=None
        )
        if resp is None:
            return None
        name, size, off = resp
        if oid in self._pins:
            # Already pinned once by us; drop the extra server-side pin.
            self._queue_release(oid)
        else:
            self._pins[oid] = name
        shm = self._map(name)
        return shm.buf[off:off + size]

    def wrap_views(self, oid: ObjectID, buffers: list) -> list:
        """Wrap the zero-copy buffer slices deserialization will alias in
        refcount-probeable handles and track them: release() only drops the
        server-side pin (and with it the arena extent) once no live view
        remains.  A bare memoryview can't detect downstream aliasing — the
        buffer-protocol chain re-exports from the underlying mapping, so
        probing mv.release() misses a numpy array built on a slice.  A
        numpy wrapper CAN: every consumer's base chain holds a reference to
        it, so its refcount returning to baseline proves the views died."""
        if not buffers:
            return buffers
        import numpy as _np

        wrappers = [_np.frombuffer(b, dtype=_np.uint8) for b in buffers]
        with self._view_lock:
            self._views.setdefault(oid, []).extend(wrappers)
        return wrappers

    def contains(self, oid: ObjectID) -> bool:
        return self._conn.call_sync("plasma_contains", {"oid": oid.binary()})

    @staticmethod
    def _views_releasable(views: list) -> bool:
        """True once no deserialized value still aliases the mapped bytes:
        each wrapper's refcount is back to baseline (the tracked list entry
        + the loop binding + getrefcount's argument)."""
        import sys

        return all(sys.getrefcount(w) <= 3 for w in views)

    def release(self, oid: ObjectID) -> None:
        """Drop our hold on a mapped object.  The server-side pin is only
        released when no deserialized value still aliases the mapping —
        plasma's pin-until-last-view contract, enforced client-side because
        shared slabs have no per-object unlink safety net.  Never blocks:
        the actual release rides the coalesced notify batch."""
        with self._view_lock:
            views = self._views.pop(oid, None)
            if views is not None and not self._views_releasable(views):
                # still aliased: park it; the flush loop re-probes until the
                # views die, then the pin drops
                self._views[oid] = views
                self._deferred_release.add(oid)
                return
            self._deferred_release.discard(oid)
        name = self._pins.pop(oid, None)
        if name is None:
            return
        if not self._conn.closed:
            self._queue_release(oid)
        if not _is_slab_name(name) and name not in self._pins.values():
            # per-object segment: drop the mapping with the last release
            with self._maps_lock:
                shm = self._maps.pop(name, None)
            if shm is not None:
                try:
                    shm.close()
                except BufferError:
                    pass  # inband bytes() copies can't alias, but be safe

    def _retry_deferred_releases(self) -> None:
        with self._view_lock:
            retry = [oid for oid in self._deferred_release
                     if self._views_releasable(self._views.get(oid, []))]
        for oid in retry:
            self.release(oid)

    def _queue_release(self, oid: ObjectID) -> None:
        with self._release_lock:
            self._release_buf.append(oid.binary())
            if len(self._release_buf) > 1:
                return  # a flush is already scheduled for this burst
        try:
            self._io.loop.call_soon_threadsafe(self._flush_releases)
        except RuntimeError:
            pass  # loop closed: shutdown path

    def _flush_releases(self) -> None:
        with self._release_lock:
            oids, self._release_buf = self._release_buf, []
        if not oids or self._conn.closed:
            return
        try:
            self._conn.notify_coalesced("plasma_release", {"oids": oids})
        except ConnectionError:
            pass

    async def _flush_loop(self):
        """Housekeeping tick: re-probe deferred releases (views may have
        died), return long-idle leased extents."""
        while not self._closed:
            await asyncio.sleep(1.0)
            try:
                self._retry_deferred_releases()
                self.return_idle_extents()
            except Exception:
                logger.exception("plasma client flush tick failed")

    def free(self, oids: List[ObjectID]) -> None:
        try:
            self._conn.call_sync("plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass

    def free_async(self, oids: List[ObjectID]) -> None:
        """Coalesced fire-and-forget local delete — the owner's fast path
        for out-of-scope objects (the GCS broadcast still sweeps remote
        copies; local capacity frees without waiting on that hop)."""
        try:
            self._conn.notify_coalesced_threadsafe(
                "plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass

    def close(self) -> None:
        """Flush pending control traffic (worker teardown must not leak
        pins/extents server-side: the store's conn cleanup would get them,
        but an orderly flush keeps accounting exact when the conn outlives
        us)."""
        self._closed = True
        try:
            self._flush_task.cancel()
        except Exception:
            pass
        self.return_idle_extents(force=True)
        with self._release_lock:
            oids, self._release_buf = self._release_buf, []
        if oids and not self._conn.closed:
            try:
                self._conn.notify_sync("plasma_release", {"oids": oids},
                                       timeout=2.0)
            except Exception:
                pass


class RemotePlasmaClient:
    """RPC-only plasma access for drivers on a DIFFERENT machine than their
    nodelet (reference role: Ray Client, util/client/ — a remote REPL drives
    the cluster without local shared memory).  Same surface as PlasmaClient;
    the data path is the chunked fetch RPC instead of shm mapping, and puts
    ship bytes inline for the nodelet to write into its store."""

    def __init__(self, io, conn):
        self._io = io
        self._conn = conn

    def put(self, oid: ObjectID, flat) -> None:
        self._put_bytes(oid, flat)

    def put_serialized(self, oid: ObjectID, ser) -> None:
        """Stream the frame per chunk straight from the SerializedObject's
        segments — no flattened intermediate copy, so a large ray:// put
        peaks at one chunk of extra memory instead of 2x the payload."""
        total = ser.total_frame_bytes()
        chunk = RayConfig.fetch_chunk_bytes
        if total <= chunk:
            # One scatter-gather write into a single frame buffer; the RPC
            # layer then ships it out-of-band (PickleBuffer) — exactly one
            # copy on this side instead of the old to_bytes() + bytes()
            # double cast.
            flat = bytearray(total)
            ser.write_into(flat)
            self._put_bytes(oid, memoryview(flat))
            return
        deadline = time.monotonic() + 30.0
        while True:
            try:
                resp = self._conn.call_sync("plasma_put_begin",
                                            {"oid": oid.binary(),
                                             "size": total})
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        if resp.get("exists"):
            return
        try:
            off = 0
            for part in ser.iter_frame(chunk):
                # PickleBuffer rides the RPC pickle-5 out-of-band path: the
                # chunk is written to the socket segment-wise, never
                # flattened into an intermediate bytes.  call_sync blocks
                # until the reply, so the source view stays live for the
                # whole write.
                self._conn.call_sync("plasma_put_chunk",
                                     {"oid": oid.binary(), "offset": off,
                                      "data": pickle.PickleBuffer(part)})
                off += part.nbytes
            self._conn.call_sync("plasma_seal", {"oid": oid.binary()})
        except BaseException:
            try:
                self._conn.call_sync("plasma_put_abort",
                                     {"oid": oid.binary()})
            except Exception:
                pass
            raise

    def wrap_views(self, oid: ObjectID, buffers: list) -> list:
        return buffers  # gets are RPC copies: nothing aliases shared memory

    def _put_bytes(self, oid: ObjectID, data) -> None:
        """Small puts ride one frame; large ones stream in chunks so a
        multi-GiB ray.put from a ray:// driver never balloons either end's
        memory with a monolithic message (gets were already chunked)."""
        data = data if isinstance(data, memoryview) else memoryview(data)
        chunk = RayConfig.fetch_chunk_bytes
        deadline = time.monotonic() + 30.0
        while True:
            try:
                if data.nbytes <= chunk:
                    self._conn.call_sync("plasma_put_bytes",
                                         {"oid": oid.binary(),
                                          "data": pickle.PickleBuffer(data)})
                    return
                resp = self._conn.call_sync("plasma_put_begin",
                                            {"oid": oid.binary(),
                                             "size": data.nbytes})
                if resp.get("exists"):
                    return
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        try:
            off = 0
            while off < data.nbytes:
                part = data[off:off + chunk]
                self._conn.call_sync("plasma_put_chunk",
                                     {"oid": oid.binary(), "offset": off,
                                      "data": pickle.PickleBuffer(part)})
                off += part.nbytes
            self._conn.call_sync("plasma_seal", {"oid": oid.binary()})
        except BaseException:
            try:
                self._conn.call_sync("plasma_put_abort",
                                     {"oid": oid.binary()})
            except Exception:
                pass
            raise

    def get_mapped(self, oid: ObjectID, timeout=None):
        """Wait server-side (plasma_get pins), then stream chunks over RPC."""
        resp = self._conn.call_sync(
            "plasma_get", {"oid": oid.binary(), "timeout": timeout},
            timeout=None)
        if resp is None:
            return None
        _name, size, _off = resp
        try:
            out = bytearray(size)
            off = 0
            chunk = RayConfig.fetch_chunk_bytes
            while off < size:
                r = self._conn.call_sync(
                    "fetch_object_chunk",
                    {"oid": oid.binary(), "off": off,
                     "len": min(chunk, size - off)})
                if r is None:
                    return None  # evicted mid-fetch; caller retries/recovers
                out[off:off + len(r["data"])] = r["data"]
                off += len(r["data"])
            return memoryview(out)
        finally:
            try:
                self._conn.call_sync("plasma_release",
                                     {"oids": [oid.binary()]})
            except ConnectionError:
                pass

    def contains(self, oid: ObjectID) -> bool:
        return self._conn.call_sync("plasma_contains", {"oid": oid.binary()})

    def release(self, oid: ObjectID) -> None:
        pass  # no local mapping to drop; the pin is released in get_mapped

    def free(self, oids) -> None:
        try:
            self._conn.call_sync(
                "plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass

    def free_async(self, oids) -> None:
        try:
            self._conn.notify_coalesced_threadsafe(
                "plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass

    def return_idle_extents(self, force: bool = False) -> None:
        pass  # no extent leases over the remote data path

    def close(self) -> None:
        pass


def register_store_handlers(handlers: dict, store: PlasmaStore, waiters: dict,
                            on_miss=None, on_full=None) -> None:
    """Wire plasma_* RPC methods into a nodelet server handler table.

    ``waiters`` maps ObjectID -> list of asyncio futures resolved when the object
    becomes local; the nodelet's pull manager also resolves these.  ``on_miss(oid)``
    is called (on the loop) when a get targets a non-local object — the nodelet's
    pull manager uses it to start fetching from a remote node (reference:
    pull_manager.h:52).  ``on_full()`` is called when an extent lease hits
    store-full — the nodelet broadcasts an extent-reclaim hint so other
    clients hand back idle leases before the requester's retry.
    """

    def _wake_waiters(oid):
        for fut in waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    def _consume_leased(conn, slab: str, off: int, alen: int) -> None:
        """Remove a sealed sub-range from this connection's leased set."""
        leased = conn.context.get("plasma_extents")
        if not leased:
            return
        runs = leased.get(slab)
        if not runs:
            return
        end = off + alen
        for i, run in enumerate(runs):
            r_off, r_len = run
            if r_off <= off and end <= r_off + r_len:
                pieces = []
                if off > r_off:
                    pieces.append([r_off, off - r_off])
                if end < r_off + r_len:
                    pieces.append([end, r_off + r_len - end])
                runs[i:i + 1] = pieces
                if not runs:
                    del leased[slab]
                return

    async def plasma_lease_extents(conn, msg):
        """Bulk extent lease: the put fast path's only RPC.  Piggybacks
        extent returns so a client's retry-after-full hands capacity back in
        the same frame."""
        for slab, off, ln in msg.get("returns") or ():
            _consume_leased(conn, slab, off, ln)
            store.free_extent(slab, off, ln)
        try:
            extents = store.lease_extents(msg["bytes"], msg["contig"])
        except ObjectStoreFullError:
            if on_full is not None:
                on_full()
            raise
        leased = conn.context.setdefault("plasma_extents", {})
        for slab, off, ln in extents:
            leased.setdefault(slab, []).append([off, ln])
        return {"extents": [list(e) for e in extents]}

    async def plasma_return_extents(conn, msg):
        for slab, off, ln in msg.get("extents") or ():
            _consume_leased(conn, slab, off, ln)
            store.free_extent(slab, off, ln)
        return True

    async def plasma_seal_extent(conn, msg):
        """Fused put/seal: register the object the client already wrote into
        its leased extent (fire-and-forget; rides the coalesced batch)."""
        oid = ObjectID(msg["oid"])
        _consume_leased(conn, msg["slab"], msg["off"], msg["alen"])
        store.seal_extent(oid, msg["slab"], msg["off"], msg["size"],
                          msg["alen"])
        _wake_waiters(oid)
        return True

    async def plasma_create(conn, msg):
        oid = ObjectID(msg["oid"])
        if store.contains(oid):
            return {"exists": True}
        name = store.create(oid, msg["size"])
        conn.context.setdefault("plasma_creating", set()).add(oid)
        return {"name": name, "exists": False}

    async def plasma_seal(conn, msg):
        oid = ObjectID(msg["oid"])
        store.seal(oid)
        conn.context.get("plasma_creating", set()).discard(oid)
        _wake_waiters(oid)
        return True

    def _track_pin(conn, oid):
        pins = conn.context.setdefault("plasma_pins", {})
        pins[oid] = pins.get(oid, 0) + 1

    async def plasma_get(conn, msg):
        oid = ObjectID(msg["oid"])
        timeout = msg.get("timeout")
        entry = store.get_local(oid)
        if entry is not None:
            _track_pin(conn, oid)
            return entry
        fut = asyncio.get_event_loop().create_future()
        waiters.setdefault(oid, []).append(fut)
        if on_miss is not None:
            on_miss(oid)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            lst = waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:
                    pass
                if not lst:
                    del waiters[oid]
            return None
        entry = store.get_local(oid)
        if entry is not None:
            _track_pin(conn, oid)
        return entry

    async def plasma_put_bytes(conn, msg):
        """Client-mode put: the driver ships bytes; this node materializes
        the object in its store (reference: Ray Client proxying ray.put)."""
        oid = ObjectID(msg["oid"])
        # write through the store's own mapping (a raw SharedMemory attach
        # here would double-register with the resource tracker)
        store.write_and_seal(oid, memoryview(msg["data"]))
        for fut in waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return True

    async def plasma_put_begin(conn, msg):
        """Chunked client-mode put: allocate the landing entry (reference:
        chunked object transfer, object_manager.proto — a multi-GiB put must
        not ride one RPC frame on either end)."""
        oid = ObjectID(msg["oid"])
        if store.contains(oid):
            return {"exists": True}
        store.create(oid, msg["size"])
        # tracked like plasma_create: a driver dying mid-put must not leak
        # the unsealed entry (cleanup_client_connection sweeps this set)
        conn.context.setdefault("plasma_creating", set()).add(oid)
        return {"exists": False}

    async def plasma_put_chunk(conn, msg):
        oid = ObjectID(msg["oid"])
        off = msg["offset"]
        data = msg["data"]
        store.write_buffer(oid)[off:off + len(data)] = data

    async def plasma_put_abort(conn, msg):
        oid = ObjectID(msg["oid"])
        store.abort(oid)
        conn.context.get("plasma_creating", set()).discard(oid)
        return True

    async def plasma_contains(conn, msg):
        return store.contains(ObjectID(msg["oid"]))

    async def plasma_wait(conn, msg):
        """Block until the object is sealed locally (or timeout) WITHOUT
        pinning or mapping it — the event source behind ray.wait's
        plasma-resident arm.  A bare contains-poll costs a full
        wait_poll_interval_ms of latency per streamed item; parking on the
        store's seal waiters delivers the wakeup the moment the producer
        seals."""
        oid = ObjectID(msg["oid"])
        if store.contains(oid):
            return True
        fut = asyncio.get_event_loop().create_future()
        waiters.setdefault(oid, []).append(fut)
        try:
            await asyncio.wait_for(fut, msg.get("timeout"))
        except asyncio.TimeoutError:
            lst = waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:
                    pass
                if not lst:
                    del waiters[oid]
            return False
        return store.contains(oid)

    async def plasma_release(conn, msg):
        # coalesced {"oids": [...]} releases; singular {"oid"} kept for
        # protocol-v1 peers that predate the list form (no current caller
        # sends it — see docs/WIRE_CONTRACT.md)
        oid_bins = msg.get("oids")
        if oid_bins is None:
            oid_bins = [msg["oid"]]
        pins = conn.context.get("plasma_pins", {})
        for b in oid_bins:
            oid = ObjectID(b)
            store.release(oid)
            if pins.get(oid, 0) > 1:
                pins[oid] -= 1
            else:
                pins.pop(oid, None)
        return True

    async def plasma_delete(conn, msg):
        for b in msg["oids"]:
            store.delete(ObjectID(b))
        return True

    async def plasma_stats(conn, msg):
        return store.stats()

    handlers.update(
        plasma_put_bytes=plasma_put_bytes,
        plasma_put_begin=plasma_put_begin,
        plasma_put_chunk=plasma_put_chunk,
        plasma_put_abort=plasma_put_abort,
        plasma_create=plasma_create,
        plasma_seal=plasma_seal,
        plasma_lease_extents=plasma_lease_extents,
        plasma_return_extents=plasma_return_extents,
        plasma_seal_extent=plasma_seal_extent,
        plasma_get=plasma_get,
        plasma_contains=plasma_contains,
        plasma_wait=plasma_wait,
        plasma_release=plasma_release,
        plasma_delete=plasma_delete,
        plasma_stats=plasma_stats,
    )


def cleanup_client_connection(store: PlasmaStore, conn,
                              waiters: Optional[dict] = None) -> None:
    """Release a dead client's pins, half-written creates, and leased-but-
    unsealed extents (reference: plasma store disconnect cleanup,
    plasma/store.cc DisconnectClient)."""
    for oid, n in conn.context.pop("plasma_pins", {}).items():
        for _ in range(n):
            store.release(oid)
    for oid in conn.context.pop("plasma_creating", set()):
        e = store.objects.get(oid)
        if e is not None and not e.sealed:
            store.delete(oid)
            # Crash consistency: gets parked on an object its creator never
            # sealed must not burn their full timeout -- the primary copy
            # died with the client.  Waking the future makes plasma_get
            # re-check the store, find nothing, and return a miss that the
            # owner-side recovery/retry path handles immediately.
            if waiters is not None:
                for fut in waiters.pop(oid, []):
                    if not fut.done():
                        fut.set_result(False)
    for slab, runs in conn.context.pop("plasma_extents", {}).items():
        # leased-but-unsealed extents return to the free list: a SIGKILLed
        # client's runs are re-leasable by the next client immediately
        for off, ln in runs:
            store.free_extent(slab, off, ln)
