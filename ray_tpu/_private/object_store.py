"""Shared-memory object store: the plasma equivalent.

Counterpart of the reference's plasma store (reference: src/ray/object_manager/plasma/
store.h:55, object_lifecycle_manager.h:101, eviction_policy.h, plasma_allocator.cc) and
the client side (src/ray/core_worker/store_provider/plasma_store_provider.h:88).

Design, TPU-host-native rather than a translation:

- One store per node, hosted inside the nodelet (raylet-equivalent) process.  Objects
  live in POSIX shared memory (``/dev/shm`` via ``multiprocessing.shared_memory``),
  one segment per object.  The reference instead dlmalloc's one big mmap arena and
  passes fds (plasma/fling.cc); per-object segments let clients attach by *name* over
  the normal RPC channel — no fd-passing — at the cost of one ``memfd`` per object,
  which is fine at the object counts a training cluster sees and removes the whole
  allocator (XLA owns device memory; host shm is a staging area).
- Zero-copy reads: clients map the segment and deserialize with pickle-5 buffers
  pointing straight into it (numpy arrays alias shm).  The mapping outlives deletion:
  POSIX keeps unlinked segments alive until the last mapping closes, which is exactly
  the pin-until-last-view semantics plasma implements with refcounts.
- Eviction & spilling: sealed, unpinned objects are spilled to disk (primary copies)
  or evicted (remote copies) in LRU order when a create needs room (reference:
  eviction_policy.h + local_object_manager.h:41 spill path, simplified into one
  component).  Restore happens transparently inside ``get``.
- Admission: creates larger than free capacity + evictable bytes raise
  ``ObjectStoreFullError`` after retrying, like the CreateRequestQueue
  (plasma/create_request_queue.h).

Server-side methods are synchronous and only called from the nodelet's event loop
(single-threaded, like the reference store's single io_context thread).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import RayConfig
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

logger = logging.getLogger(__name__)


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment without registering with resource_tracker.

    The tracker would try to unlink segments owned by the store when *this*
    process exits; only the store unlinks.
    """
    shm = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    orig_close = shm.close

    def _close_tolerant():
        try:
            orig_close()
        except BufferError:
            # A zero-copy numpy view still aliases the mapping (interpreter
            # shutdown / GC); the segment is store-owned, leaking the mapping
            # until process exit matches plasma's pin semantics.
            pass

    shm.close = _close_tolerant
    return shm


class _Entry:
    __slots__ = (
        "oid", "shm", "size", "alloc", "sealed", "pins", "last_access",
        "is_primary", "spilled_path", "ever_viewed",
    )

    def __init__(self, oid: ObjectID, shm: Optional[shared_memory.SharedMemory], size: int, is_primary: bool,
                 alloc: Optional[int] = None):
        self.oid = oid
        self.shm = shm
        self.size = size
        self.alloc = alloc if alloc is not None else size  # segment bytes
        self.sealed = False
        self.pins = 0  # outstanding client pins; only 0-pin objects evict
        self.last_access = time.monotonic()
        self.is_primary = is_primary  # created locally by owner (vs pulled copy)
        self.spilled_path: Optional[str] = None
        # True once ANY reader (client mapping or server-side view) may have
        # aliased the segment.  Such segments must be unlinked, never pooled:
        # a lingering zero-copy view must keep seeing the old bytes (plasma's
        # pin-until-last-view contract).
        self.ever_viewed = False


class PlasmaStore:
    """Node-local shared-memory store. All methods run on the nodelet loop."""

    def __init__(self, capacity_bytes: int, spill_dir: Optional[str] = None, node_id_hex: str = ""):
        self.capacity = capacity_bytes
        self.used = 0
        self.objects: Dict[ObjectID, _Entry] = {}
        self.spill_dir = spill_dir
        self.node_id_hex = node_id_hex
        self._seq = 0
        # Callbacks wired by the nodelet: object sealed / deleted locally
        # (feeds the GCS object directory, reference: ownership_based_object_directory.h).
        self.on_sealed = None
        self.on_deleted = None
        self.num_spilled = 0
        self.bytes_spilled = 0
        # Segment pool: freed never-viewed segments keyed by allocation
        # bucket, kept MAPPED so their pages stay physically allocated.  A
        # fresh 64 MiB segment costs ~90 ms of first-touch page faults on
        # write; a pooled one writes at memcpy speed.  This is the per-object
        #-segment equivalent of the reference's one-arena dlmalloc design
        # (plasma/plasma_allocator.cc), where pages are faulted once per
        # store lifetime.
        self._seg_pool: Dict[int, List[shared_memory.SharedMemory]] = {}
        self._pool_bytes = 0
        self._pool_cap = min(256 * 1024 * 1024, capacity_bytes // 4)

    # Segments below this aren't pooled: their first-touch cost is trivial
    # and page-rounding would distort small-capacity accounting.
    _POOL_MIN_SEGMENT = 1024 * 1024

    @classmethod
    def _bucket(cls, size: int) -> int:
        """Round poolable allocations to whole pages; repeated puts of
        same-shaped payloads (the common steady-state) then land in matching
        buckets."""
        if size < cls._POOL_MIN_SEGMENT:
            return max(size, 1)
        return (size + 4095) & ~4095

    def _pool_take(self, bucket: int) -> Optional[shared_memory.SharedMemory]:
        pool = self._seg_pool.get(bucket)
        if pool:
            self._pool_bytes -= bucket
            return pool.pop()
        return None

    def _pool_reclaim(self, need: int) -> None:
        """Unlink pooled segments (largest first) to free real memory."""
        freed = 0
        for bucket in sorted(self._seg_pool, reverse=True):
            pool = self._seg_pool[bucket]
            while pool and freed < need:
                shm = pool.pop()
                self._pool_bytes -= bucket
                freed += bucket
                try:
                    shm.unlink()
                except FileNotFoundError:
                    pass
                shm.close()
            if freed >= need:
                break

    # -- helpers -------------------------------------------------------------
    def _segment_name(self) -> str:
        self._seq += 1
        return f"rtpu_{self.node_id_hex[:8]}_{os.getpid()}_{self._seq}"

    def _evictable(self) -> List[_Entry]:
        return [
            e for e in self.objects.values()
            if e.sealed and e.pins == 0 and e.shm is not None
        ]

    def _ensure_room(self, size: int) -> bool:
        if self.used + self._pool_bytes + size <= self.capacity:
            return True
        # Pooled (free but still-mapped) segments are the cheapest room.
        self._pool_reclaim(self.used + self._pool_bytes + size - self.capacity)
        if self.used + self._pool_bytes + size <= self.capacity:
            return True
        victims = sorted(self._evictable(), key=lambda e: e.last_access)
        for e in victims:
            if self.used + self._pool_bytes + size <= self.capacity:
                break
            if e.is_primary:
                if self.spill_dir:
                    self._spill(e)
                # No spill dir: a primary copy is the ONLY copy — never delete
                # it to make room; the create fails instead.
            else:
                # pool_ok=False: this eviction exists to FREE memory — moving
                # the segment into the pool would make no progress and spill
                # further victims for nothing.
                self._drop_shm(e, pool_ok=False)
                if not e.spilled_path:
                    del self.objects[e.oid]
                    if self.on_deleted:
                        self.on_deleted(e.oid)
        self._pool_reclaim(self.used + self._pool_bytes + size - self.capacity)
        return self.used + self._pool_bytes + size <= self.capacity

    def _spill(self, e: _Entry) -> None:
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, e.oid.hex())
        with open(path, "wb") as f:
            f.write(e.shm.buf[: e.size])
        e.spilled_path = path
        self.num_spilled += 1
        self.bytes_spilled += e.size
        # spilling exists to free memory: bypass the pool
        self._drop_shm(e, pool_ok=False)

    def _restore(self, e: _Entry) -> None:
        alloc = self._bucket(e.size)
        shm = self._pool_take(alloc)
        if shm is None:
            if not self._ensure_room(alloc):
                raise ObjectStoreFullError(
                    f"cannot restore {e.oid}: store full ({self.used}/{self.capacity})"
                )
            shm = shared_memory.SharedMemory(
                name=self._segment_name(), create=True, size=alloc)
        with open(e.spilled_path, "rb") as f:
            f.readinto(shm.buf)
        e.shm = shm
        e.alloc = alloc
        e.ever_viewed = False
        self.used += alloc

    def _drop_shm(self, e: _Entry, pool_ok: bool = True) -> None:
        if e.shm is not None:
            self.used -= e.alloc
            if pool_ok and not e.ever_viewed and \
                    e.alloc >= self._POOL_MIN_SEGMENT and \
                    self._pool_bytes + e.alloc <= self._pool_cap:
                # Never aliased by a reader: safe to recycle with pages hot.
                self._seg_pool.setdefault(e.alloc, []).append(e.shm)
                self._pool_bytes += e.alloc
            else:
                try:
                    e.shm.unlink()
                except FileNotFoundError:
                    pass
                try:
                    e.shm.close()
                except BufferError:
                    # A transient server-side view (push/spill in flight)
                    # still aliases the buffer; the segment is unlinked so
                    # the pages are reclaimed when the mapping dies with the
                    # view.
                    pass
            e.shm = None

    # -- API -----------------------------------------------------------------
    def create(self, oid: ObjectID, size: int, is_primary: bool = True) -> str:
        """Allocate a segment for oid; returns the shm name for the client to map."""
        if oid in self.objects:
            e = self.objects[oid]
            if e.sealed:
                raise FileExistsError(f"object {oid} already sealed")
            # Re-create (e.g. failed writer): drop the half-written segment.
            self._drop_shm(e)
            del self.objects[oid]
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity {self.capacity}"
            )
        alloc = self._bucket(size)
        shm = self._pool_take(alloc)
        if shm is None:
            if not self._ensure_room(alloc):
                raise ObjectStoreFullError(
                    f"store full: need {size}, used {self.used}/{self.capacity}, "
                    f"evictable {sum(x.size for x in self._evictable())}"
                )
            shm = shared_memory.SharedMemory(
                name=self._segment_name(), create=True, size=alloc)
        e = _Entry(oid, shm, size, is_primary, alloc=alloc)
        self.objects[oid] = e
        self.used += alloc
        return shm.name

    def seal(self, oid: ObjectID) -> None:
        e = self.objects[oid]
        e.sealed = True
        e.last_access = time.monotonic()
        if self.on_sealed:
            self.on_sealed(oid, e.size)

    def abort(self, oid: ObjectID) -> None:
        """Drop an unsealed (half-written) entry, e.g. a failed chunked pull."""
        e = self.objects.get(oid)
        if e is not None and not e.sealed:
            self._drop_shm(e)
            del self.objects[oid]

    def write_buffer(self, oid: ObjectID):
        """Writable view of an unsealed entry (chunked transfer landing pad)."""
        e = self.objects[oid]
        assert not e.sealed, f"object {oid} already sealed"
        e.ever_viewed = True  # returned view may outlive the entry
        return e.shm.buf

    def write_and_seal(self, oid: ObjectID, data: memoryview, is_primary: bool = True) -> None:
        """Server-side path used by object transfer (pull) and spill restore."""
        if self.contains(oid):
            return
        name = self.create(oid, data.nbytes, is_primary=is_primary)
        e = self.objects[oid]
        e.shm.buf[: data.nbytes] = data
        del name
        self.seal(oid)

    def contains(self, oid: ObjectID) -> bool:
        e = self.objects.get(oid)
        return e is not None and e.sealed

    def get_local(self, oid: ObjectID, pin: bool = True) -> Optional[Tuple[Optional[str], int]]:
        """Return (shm_name, size) for a sealed local object, restoring from spill.

        shm_name is None only if the object is unknown. Pins the object so it
        survives until the client releases it.
        """
        e = self.objects.get(oid)
        if e is None or not e.sealed:
            return None
        if e.shm is None and e.spilled_path:
            self._restore(e)
        e.last_access = time.monotonic()
        e.ever_viewed = True  # client maps by name: segment can't be pooled
        if pin:
            e.pins += 1
        return (e.shm.name, e.size)

    def read_bytes(self, oid: ObjectID) -> Optional[memoryview]:
        """Server-side view of the object payload (for node-to-node push)."""
        e = self.objects.get(oid)
        if e is None or not e.sealed:
            return None
        if e.shm is None and e.spilled_path:
            self._restore(e)
        e.last_access = time.monotonic()
        e.ever_viewed = True  # returned view may outlive the entry
        return e.shm.buf[: e.size]

    def release(self, oid: ObjectID) -> None:
        e = self.objects.get(oid)
        if e is not None and e.pins > 0:
            e.pins -= 1

    def delete(self, oid: ObjectID) -> None:
        e = self.objects.pop(oid, None)
        if e is None:
            return
        self._drop_shm(e)
        if e.spilled_path:
            try:
                os.remove(e.spilled_path)
            except OSError:
                pass
        if self.on_deleted:
            self.on_deleted(oid)

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "pooled": self._pool_bytes,
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "bytes_spilled": self.bytes_spilled,
        }

    def shutdown(self) -> None:
        for oid in list(self.objects):
            self.delete(oid)
        self._pool_reclaim(self._pool_bytes)


class PlasmaClient:
    """Client-side zero-copy access, used by CoreWorker.

    Methods are synchronous and called from the user thread; RPC metadata rides the
    worker's IO loop, the data path is direct shm mapping (reference:
    plasma_store_provider.h:88; zero-copy get semantics of plasma).
    """

    # Write-mapping cache budget: segment names recur when the store's pool
    # recycles a segment; re-attaching costs a full round of soft page
    # faults, so keeping the mapping makes repeated large puts run at
    # memcpy speed.  Names are never reused for a different segment (the
    # store's name sequence is monotonic), so a cached mapping is always
    # the right inode.
    _WRITE_CACHE_BYTES = 256 * 1024 * 1024
    # A mapping of a segment the server has since unlinked can never hit
    # again (the name is gone forever) but still pins its pages outside the
    # store's accounting — drop any entry idle this long so stale mappings
    # are bounded in time, not only by budget pressure.
    _WRITE_CACHE_IDLE_S = 30.0

    def __init__(self, io, conn):
        # io: EventLoopThread, conn: Connection to the local nodelet
        self._io = io
        self._conn = conn
        self._mappings: Dict[ObjectID, shared_memory.SharedMemory] = {}
        # name -> [shm, in_use_count]; LRU order.  Guarded by _write_lock:
        # puts run concurrently on executor threads, and eviction must never
        # close a mapping another thread is mid-write on (in_use > 0).
        self._write_cache: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()
        self._write_cache_bytes = 0
        self._write_lock = threading.Lock()

    def _map_for_write(self, name: str) -> Tuple[shared_memory.SharedMemory, bool]:
        """Returns (mapping, cached).  Cached mappings must be released via
        _release_write (not closed); uncached ones are the caller's to
        close."""
        now = time.monotonic()
        with self._write_lock:
            # time-bounded pruning of idle mappings (see _WRITE_CACHE_IDLE_S)
            for k in [k for k, v in self._write_cache.items()
                      if v[1] == 0 and now - v[2] > self._WRITE_CACHE_IDLE_S]:
                old = self._write_cache.pop(k)
                self._write_cache_bytes -= old[0].size
                old[0].close()
            ent = self._write_cache.get(name)
            if ent is not None:
                ent[1] += 1
                ent[2] = now
                self._write_cache.move_to_end(name)
                return ent[0], True
        shm = _attach_shm(name)
        size = shm.size
        if size > self._WRITE_CACHE_BYTES:
            return shm, False
        with self._write_lock:
            if name in self._write_cache:  # raced with another thread
                ent = self._write_cache[name]
                ent[1] += 1
                ent[2] = now
                to_close = shm
            else:
                while self._write_cache_bytes + size > self._WRITE_CACHE_BYTES:
                    victim = next((k for k, v in self._write_cache.items()
                                   if v[1] == 0), None)
                    if victim is None:
                        break  # everything busy: run over budget briefly
                    old = self._write_cache.pop(victim)
                    self._write_cache_bytes -= old[0].size
                    old[0].close()
                self._write_cache[name] = [shm, 1, now]
                self._write_cache_bytes += size
                return shm, True
        to_close.close()
        return ent[0], True

    def _release_write(self, name: str) -> None:
        with self._write_lock:
            ent = self._write_cache.get(name)
            if ent is not None:
                ent[1] = max(ent[1] - 1, 0)
                ent[2] = time.monotonic()

    def put(self, oid: ObjectID, flat: memoryview | bytes) -> None:
        """Create + write + seal one object from an already-flat frame."""
        nbytes = flat.nbytes if isinstance(flat, memoryview) else len(flat)
        got = self._create(oid, nbytes)
        if got is None:
            return
        name, shm, cached = got
        try:
            shm.buf[:nbytes] = flat
        finally:
            if cached:
                self._release_write(name)
            else:
                shm.close()
        self._conn.call_sync("plasma_seal", {"oid": oid.binary()})

    def put_serialized(self, oid: ObjectID, ser) -> None:
        """Create + write + seal, streaming a SerializedObject's segments
        straight into the mapped segment — no intermediate flat copy (the
        to_bytes() round-trip doubles the memcpy cost of a large put)."""
        nbytes = ser.total_frame_bytes()
        got = self._create(oid, nbytes)
        if got is None:
            return
        name, shm, cached = got
        try:
            ser.write_into(shm.buf)
        finally:
            if cached:
                self._release_write(name)
            else:
                shm.close()
        self._conn.call_sync("plasma_seal", {"oid": oid.binary()})

    def _create(self, oid: ObjectID, nbytes: int):
        """Allocate a segment, waiting out transient store-full; returns the
        mapped shm or None if the object already exists."""
        deadline = time.monotonic() + 30.0
        while True:
            try:
                resp = self._conn.call_sync("plasma_create", {"oid": oid.binary(), "size": nbytes})
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        if resp.get("exists"):
            return None
        name = resp["name"]
        shm, cached = self._map_for_write(name)
        return name, shm, cached

    def get_mapped(self, oid: ObjectID, timeout: Optional[float] = None) -> Optional[memoryview]:
        """Map a sealed object; returns a memoryview over shm or None on timeout.

        The nodelet blocks server-side until the object is local (pulling from
        remote nodes if needed), so no client-side polling.
        """
        resp = self._conn.call_sync(
            "plasma_get", {"oid": oid.binary(), "timeout": timeout}, timeout=None
        )
        if resp is None:
            return None
        name, size = resp
        if oid in self._mappings:
            # Already pinned once by us; drop the extra server-side pin.
            self._conn.call_sync("plasma_release", {"oid": oid.binary()})
            shm = self._mappings[oid]
        else:
            shm = _attach_shm(name)
            self._mappings[oid] = shm
        return shm.buf[:size]

    def contains(self, oid: ObjectID) -> bool:
        return self._conn.call_sync("plasma_contains", {"oid": oid.binary()})

    def release(self, oid: ObjectID) -> None:
        shm = self._mappings.pop(oid, None)
        if shm is not None:
            if not self._conn.closed:
                if self._io.on_loop_thread():
                    # ObjectRef.__del__ can run ON the IO loop (e.g. a task
                    # completion dropping the last hold); a blocking call_sync
                    # here would deadlock the loop, so fire-and-forget the
                    # release instead (the nodelet handles notify the same as
                    # call, minus the reply).  A ConnectionLost inside the
                    # spawned coroutine is dropped with its future — same
                    # swallow-on-teardown behavior as the sync branch.
                    self._io.spawn(
                        self._conn.notify("plasma_release", {"oid": oid.binary()}))
                else:
                    try:
                        self._conn.call_sync("plasma_release", {"oid": oid.binary()})
                    except ConnectionError:
                        pass
            # Close lazily: deserialized numpy arrays may alias this mapping.
            # POSIX keeps the pages alive until close; we close only when no
            # views exist, which we approximate by closing at release time if
            # the buffer has no exports. memoryview tracking is implicit: shm
            # keeps its own buffer; closing with live exports raises, so guard.
            try:
                shm.close()
            except BufferError:
                # A deserialized value still aliases the buffer; leak the
                # mapping (freed at process exit) — same behavior as plasma
                # pinning the object while a numpy view exists.
                pass

    def free(self, oids: List[ObjectID]) -> None:
        try:
            self._conn.call_sync("plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass


class RemotePlasmaClient:
    """RPC-only plasma access for drivers on a DIFFERENT machine than their
    nodelet (reference role: Ray Client, util/client/ — a remote REPL drives
    the cluster without local shared memory).  Same surface as PlasmaClient;
    the data path is the chunked fetch RPC instead of shm mapping, and puts
    ship bytes inline for the nodelet to write into its store."""

    def __init__(self, io, conn):
        self._io = io
        self._conn = conn

    def put(self, oid: ObjectID, flat) -> None:
        self._put_bytes(oid, flat)

    def put_serialized(self, oid: ObjectID, ser) -> None:
        buf = bytearray(ser.total_frame_bytes())
        ser.write_into(memoryview(buf))
        self._put_bytes(oid, memoryview(buf))

    def _put_bytes(self, oid: ObjectID, data) -> None:
        """Small puts ride one frame; large ones stream in chunks so a
        multi-GiB ray.put from a ray:// driver never balloons either end's
        memory with a monolithic message (gets were already chunked)."""
        data = data if isinstance(data, memoryview) else memoryview(data)
        chunk = RayConfig.fetch_chunk_bytes
        deadline = time.monotonic() + 30.0
        while True:
            try:
                if data.nbytes <= chunk:
                    self._conn.call_sync("plasma_put_bytes",
                                         {"oid": oid.binary(),
                                          "data": bytes(data)})
                    return
                resp = self._conn.call_sync("plasma_put_begin",
                                            {"oid": oid.binary(),
                                             "size": data.nbytes})
                if resp.get("exists"):
                    return
                break
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(RayConfig.object_store_full_delay_ms / 1000.0)
        try:
            off = 0
            while off < data.nbytes:
                part = data[off:off + chunk]
                self._conn.call_sync("plasma_put_chunk",
                                     {"oid": oid.binary(), "offset": off,
                                      "data": bytes(part)})
                off += part.nbytes
            self._conn.call_sync("plasma_seal", {"oid": oid.binary()})
        except BaseException:
            try:
                self._conn.call_sync("plasma_put_abort",
                                     {"oid": oid.binary()})
            except Exception:
                pass
            raise

    def get_mapped(self, oid: ObjectID, timeout=None):
        """Wait server-side (plasma_get pins), then stream chunks over RPC."""
        resp = self._conn.call_sync(
            "plasma_get", {"oid": oid.binary(), "timeout": timeout},
            timeout=None)
        if resp is None:
            return None
        _name, size = resp
        try:
            out = bytearray(size)
            off = 0
            chunk = RayConfig.fetch_chunk_bytes
            while off < size:
                r = self._conn.call_sync(
                    "fetch_object_chunk",
                    {"oid": oid.binary(), "off": off,
                     "len": min(chunk, size - off)})
                if r is None:
                    return None  # evicted mid-fetch; caller retries/recovers
                out[off:off + len(r["data"])] = r["data"]
                off += len(r["data"])
            return memoryview(out)
        finally:
            try:
                self._conn.call_sync("plasma_release", {"oid": oid.binary()})
            except ConnectionError:
                pass

    def contains(self, oid: ObjectID) -> bool:
        return self._conn.call_sync("plasma_contains", {"oid": oid.binary()})

    def release(self, oid: ObjectID) -> None:
        pass  # no local mapping to drop; the pin is released in get_mapped

    def free(self, oids) -> None:
        try:
            self._conn.call_sync(
                "plasma_delete", {"oids": [o.binary() for o in oids]})
        except ConnectionError:
            pass


def register_store_handlers(handlers: dict, store: PlasmaStore, waiters: dict,
                            on_miss=None) -> None:
    """Wire plasma_* RPC methods into a nodelet server handler table.

    ``waiters`` maps ObjectID -> list of asyncio futures resolved when the object
    becomes local; the nodelet's pull manager also resolves these.  ``on_miss(oid)``
    is called (on the loop) when a get targets a non-local object — the nodelet's
    pull manager uses it to start fetching from a remote node (reference:
    pull_manager.h:52).
    """
    import asyncio

    async def plasma_create(conn, msg):
        oid = ObjectID(msg["oid"])
        if store.contains(oid):
            return {"exists": True}
        name = store.create(oid, msg["size"])
        conn.context.setdefault("plasma_creating", set()).add(oid)
        return {"name": name, "exists": False}

    async def plasma_seal(conn, msg):
        oid = ObjectID(msg["oid"])
        store.seal(oid)
        conn.context.get("plasma_creating", set()).discard(oid)
        for fut in waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return True

    def _track_pin(conn, oid):
        pins = conn.context.setdefault("plasma_pins", {})
        pins[oid] = pins.get(oid, 0) + 1

    async def plasma_get(conn, msg):
        oid = ObjectID(msg["oid"])
        timeout = msg.get("timeout")
        entry = store.get_local(oid)
        if entry is not None:
            _track_pin(conn, oid)
            return entry
        fut = asyncio.get_event_loop().create_future()
        waiters.setdefault(oid, []).append(fut)
        if on_miss is not None:
            on_miss(oid)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            lst = waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:
                    pass
                if not lst:
                    del waiters[oid]
            return None
        entry = store.get_local(oid)
        if entry is not None:
            _track_pin(conn, oid)
        return entry

    async def plasma_put_bytes(conn, msg):
        """Client-mode put: the driver ships bytes; this node materializes
        the object in its store (reference: Ray Client proxying ray.put)."""
        oid = ObjectID(msg["oid"])
        # write through the store's own mapping (a raw SharedMemory attach
        # here would double-register with the resource tracker)
        store.write_and_seal(oid, memoryview(msg["data"]))
        for fut in waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return True

    async def plasma_put_begin(conn, msg):
        """Chunked client-mode put: allocate the landing entry (reference:
        chunked object transfer, object_manager.proto — a multi-GiB put must
        not ride one RPC frame on either end)."""
        oid = ObjectID(msg["oid"])
        if store.contains(oid):
            return {"exists": True}
        store.create(oid, msg["size"])
        # tracked like plasma_create: a driver dying mid-put must not leak
        # the unsealed entry (cleanup_client_connection sweeps this set)
        conn.context.setdefault("plasma_creating", set()).add(oid)
        return {"exists": False}

    async def plasma_put_chunk(conn, msg):
        oid = ObjectID(msg["oid"])
        off = msg["offset"]
        data = msg["data"]
        store.write_buffer(oid)[off:off + len(data)] = data

    async def plasma_put_abort(conn, msg):
        oid = ObjectID(msg["oid"])
        store.abort(oid)
        conn.context.get("plasma_creating", set()).discard(oid)
        return True

    async def plasma_contains(conn, msg):
        return store.contains(ObjectID(msg["oid"]))

    async def plasma_release(conn, msg):
        oid = ObjectID(msg["oid"])
        store.release(oid)
        pins = conn.context.get("plasma_pins", {})
        if pins.get(oid, 0) > 1:
            pins[oid] -= 1
        else:
            pins.pop(oid, None)
        return True

    async def plasma_delete(conn, msg):
        for b in msg["oids"]:
            store.delete(ObjectID(b))
        return True

    async def plasma_stats(conn, msg):
        return store.stats()

    handlers.update(
        plasma_put_bytes=plasma_put_bytes,
        plasma_put_begin=plasma_put_begin,
        plasma_put_chunk=plasma_put_chunk,
        plasma_put_abort=plasma_put_abort,
        plasma_create=plasma_create,
        plasma_seal=plasma_seal,
        plasma_get=plasma_get,
        plasma_contains=plasma_contains,
        plasma_release=plasma_release,
        plasma_delete=plasma_delete,
        plasma_stats=plasma_stats,
    )


def cleanup_client_connection(store: PlasmaStore, conn) -> None:
    """Release a dead client's pins and half-written creates (reference: plasma
    store disconnect cleanup, plasma/store.cc DisconnectClient)."""
    for oid, n in conn.context.pop("plasma_pins", {}).items():
        for _ in range(n):
            store.release(oid)
    for oid in conn.context.pop("plasma_creating", set()):
        e = store.objects.get(oid)
        if e is not None and not e.sealed:
            store.delete(oid)
