"""Nodelet: the per-node daemon (raylet equivalent).

Counterpart of the reference's raylet/NodeManager (reference:
src/ray/raylet/node_manager.h:119) fused with its helpers:

- worker pool: spawn/reuse/reap Python worker subprocesses
  (WorkerPool, raylet/worker_pool.h, PopWorkerCallbackAsync worker_pool.cc:186)
- lease-based local scheduler with spillback to the best node
  (ClusterTaskManager cluster_task_manager.cc:44 + LocalTaskManager dispatch loop
  local_task_manager.cc:122; hybrid policy hybrid_scheduling_policy.h:50)
- plasma store hosting + node-to-node object transfer (pull-based, chunked)
  (ObjectManager object_manager.h:117, PullManager pull_manager.h:52)
- placement-group bundle reservations (PlacementGroupResourceManager,
  raylet/placement_group_resource_manager.h) with 2PC prepare/commit/cancel
- GCS sync: register, periodic resource reports, cluster-view subscription
  (ray_syncer bidi stream equivalent), worker/actor death reporting

Design notes (TPU-host-native, not a translation): one asyncio process per node; the
plasma store lives on the nodelet loop (the reference embeds it in the raylet too);
liveness to workers is the persistent RPC connection + subprocess exit codes rather
than unix-socket heartbeats.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import fault_injection, flight_recorder, incidents, rpc
from ray_tpu._private.config import RayConfig
from ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ray_tpu._private.object_store import PlasmaStore, register_store_handlers
from ray_tpu.exceptions import ObjectStoreFullError

logger = logging.getLogger(__name__)


class _LeaseCancelled(Exception):
    """A queued lease request was cancelled by its client."""


class WorkerHandle:
    __slots__ = ("worker_id", "proc", "conn", "addr", "pid", "state", "lease_id",
                 "is_actor", "actor_id", "started_at", "idle_since",
                 "leased_since", "env_key")

    def __init__(self, worker_id: bytes, proc: Optional[subprocess.Popen],
                 env_key: str = ""):
        self.worker_id = worker_id
        self.proc = proc
        self.conn: Optional[rpc.Connection] = None
        self.addr: Optional[Tuple[str, int]] = None
        self.pid = proc.pid if proc else None
        self.state = "starting"  # starting -> idle -> leased | actor -> dead
        self.lease_id: Optional[int] = None
        self.is_actor = False
        self.actor_id: Optional[bytes] = None  # hosting this actor (re-reported on GCS reconnect)
        self.started_at = time.monotonic()
        self.idle_since = time.monotonic()
        self.leased_since = 0.0  # stamped when state flips to "leased"
        # isolation-env pool this worker belongs to ("" = default pool;
        # runtime_env.env_key of the pip/image env it was booted inside)
        self.env_key = env_key


class Bundle:
    __slots__ = ("pg_id", "index", "resources", "available", "committed")

    def __init__(self, pg_id: bytes, index: int, resources: Dict[str, float]):
        self.pg_id = pg_id
        self.index = index
        self.resources = dict(resources)
        self.available = dict(resources)
        self.committed = False


class Nodelet:
    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        session_dir: str = "/tmp/ray_tpu",
        node_name: str = "",
        labels: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.gcs_addr = gcs_addr
        self.session_dir = session_dir
        self.node_name = node_name or f"node-{self.node_id.hex()[:8]}"
        self.labels = labels or {}

        from ray_tpu._private.resources import default_node_resources

        self.resources_total = default_node_resources(resources)
        self.resources_available = dict(self.resources_total)

        cap = object_store_memory or RayConfig.object_store_memory_bytes
        self.store = PlasmaStore(
            capacity_bytes=cap,
            spill_dir=os.path.join(session_dir, "spill", self.node_id.hex()[:8]),
            node_id_hex=self.node_id.hex(),
        )
        self.store.on_sealed = self._on_object_sealed
        self.store.on_deleted = self._on_object_deleted
        self.waiters: Dict[ObjectID, List[asyncio.Future]] = {}

        self.workers: Dict[bytes, WorkerHandle] = {}
        # (future, env_key) pairs waiting for an idle worker of that pool
        self._pop_queue: deque = deque()
        self._starting_count = 0
        self._starting_by_key: Dict[str, int] = {}
        # env_key -> worker-launch adjustments (venv python / image wrap),
        # resolved once per key by _prepare_env and reused by every spawn
        self._env_launch: Dict[str, dict] = {}
        self._env_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rtpu-envs")

        self.leases: Dict[int, dict] = {}
        self._lease_seq = 0
        self._queued_leases: deque = deque()  # (msg, future) waiting for resources
        # client token -> the future its lease request currently waits on
        # (resource queue or worker pop); cancellation resolves it with
        # _LeaseCancelled (reference: CancelWorkerLease,
        # normal_task_submitter.cc lease cancellation on queue drain)
        self._lease_waiters: Dict[str, asyncio.Future] = {}

        self.bundles: Dict[Tuple[bytes, int], Bundle] = {}

        self.cluster_view: Dict[bytes, dict] = {}  # node_id -> {addr,total,available}
        self.gcs: Optional[rpc.Connection] = None
        self._peer_conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._pulls_inflight: Set[ObjectID] = set()

        self._dir_added: List[bytes] = []
        self._dir_removed: List[bytes] = []
        # resource-shape -> (last_seen_ts, resources, last_warned_ts) of
        # recently-rejected lease requests: reported (deduped per shape) as
        # autoscaler demand until the submitter's retries land somewhere
        self._infeasible_demand: Dict[tuple, tuple] = {}

        handlers = {}
        register_store_handlers(handlers, self.store, self.waiters,
                                on_miss=self._on_store_miss,
                                on_full=self._broadcast_extent_reclaim)
        for name in dir(self):
            if name.startswith("rpc_"):
                handlers[name[4:]] = getattr(self, name)
        handlers["publish"] = self._on_publish
        self.handlers = handlers
        self.server = rpc.Server(handlers, name=f"nodelet-{self.node_id.hex()[:6]}")
        self.server.on_disconnect = self._on_conn_lost
        self.addr: Tuple[str, int] = ("", 0)
        self._bg: List[asyncio.Task] = []
        self._shutting_down = False
        self._gcs_reconnecting = False
        self._disk_full = False
        # hang watchdog: (task_id hex, attempt) -> flag record of tasks
        # currently running past their threshold on this node
        self._suspected_hung: Dict[Tuple[str, int], dict] = {}

    # ------------------------------------------------------------------ boot
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        self.addr = await self.server.start(host, port)
        # This process's own black box + incident publisher (the nodelet has
        # no core worker, so incidents ride its GCS connection instead)
        flight_recorder.init_process(self.session_dir,
                                     f"nodelet-{self.node_id.hex()}")
        incidents.set_publisher(self._publish_incident)
        # Prometheus scrape endpoint for this node's merged metrics
        # (reference: the per-node metrics agent, _private/metrics_agent.py:483)
        from ray_tpu._private.metrics import default_registry, serve_metrics_http

        self.metrics_registry = default_registry
        # bind the same interface as the RPC server: a loopback-bound scrape
        # endpoint would be advertised cluster-wide yet unreachable remotely
        self.metrics_addr = await serve_metrics_http(default_registry,
                                                     host=self.addr[0] or host)
        await self._connect_gcs()
        if self.gcs.closed:  # dropped before _on_close was attached
            self._on_gcs_lost(self.gcs)
        self._bg.append(asyncio.get_event_loop().create_task(self._report_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._monitor_workers_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._flush_dir_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._fs_monitor_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._hang_watchdog_loop()))
        # The nodelet's own threads join the cluster flamegraph too (no-op
        # unless profile_hz > 0); its deltas ship via _report_loop's push.
        from ray_tpu._private import profiler

        profiler.ensure_started()
        logger.info("nodelet %s on %s:%s resources=%s",
                    self.node_id.hex()[:8], *self.addr, self.resources_total)
        return self.addr

    async def _connect_gcs(self):
        """Connect + (re)register with the GCS.  Registration always carries
        the node's FULL live state — hosted actors, PG bundles, local objects
        — so a restarted GCS reconciles its restored tables against reality
        (reference: ray_syncer resync + GcsInitData replay on GCS failover).

        self.gcs is swapped only AFTER registration succeeds, and the close
        callback is attached last: a half-initialized connection must neither
        receive resource reports (a not-yet-registered node would be told
        'unknown') nor spawn a second reconnect loop when it fails."""
        # Full handler table: the GCS calls back over this same connection
        # (lease_worker_for_actor, prepare/commit/cancel_bundle, ...).
        gcs = await rpc.connect(*self.gcs_addr, handlers=self.handlers,
                                name="nodelet->gcs")
        resp = await gcs.call("register_node", {
            "node_id": self.node_id.binary(),
            "addr": list(self.addr),
            "resources": self.resources_total,
            "labels": self.labels,
            "node_name": self.node_name,
            "object_store_capacity": self.store.capacity,
            "metrics_addr": list(getattr(self, "metrics_addr", ("", 0))),
            "actors": [
                {"actor_id": w.actor_id, "worker_addr": list(w.addr),
                 "worker_id": w.worker_id}
                for w in self.workers.values()
                if w.is_actor and w.actor_id is not None and w.addr
                and w.state != "dead"
            ],
            "bundles": [
                {"pg_id": b.pg_id, "index": b.index, "resources": b.resources}
                for b in self.bundles.values() if b.committed
            ],
            "objects": [oid.binary() for oid, e in self.store.objects.items()
                        if e.sealed],
        })
        for view in resp["cluster_view"]:
            self.cluster_view[view["node_id"]] = view
        await gcs.call("subscribe", {"channel": "resource_view"})
        await gcs.call("subscribe", {"channel": "node"})
        old, self.gcs = self.gcs, gcs
        if old is not None and old is not gcs and not old.closed:
            await old.close()
        gcs._on_close = self._on_gcs_lost

    def _on_gcs_lost(self, conn):
        if self._shutting_down or self._gcs_reconnecting:
            return
        self._gcs_reconnecting = True
        logger.warning("nodelet %s lost the GCS connection; reconnecting",
                       self.node_id.hex()[:8])
        asyncio.get_event_loop().create_task(self._gcs_reconnect_loop())

    async def _gcs_reconnect_loop(self):
        """Retry the GCS with backoff (reference: raylets reconnect to a
        restarted GCS when FT is on); give up and die after the window —
        an isolated nodelet holding a TPU chip is worse than a dead one."""
        deadline = time.monotonic() + RayConfig.gcs_reconnect_timeout_s
        delay = 0.2
        try:
            while not self._shutting_down:
                await asyncio.sleep(delay)
                try:
                    await self._connect_gcs()
                    if self.gcs.closed:
                        continue  # dropped in the attach window: retry
                    logger.info("nodelet %s re-registered with the GCS",
                                self.node_id.hex()[:8])
                    return
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    if time.monotonic() > deadline:
                        logger.error(
                            "GCS unreachable for %.0fs; nodelet exiting",
                            RayConfig.gcs_reconnect_timeout_s)
                        os._exit(1)
                    delay = min(delay * 1.5, 3.0)
        finally:
            self._gcs_reconnecting = False

    async def stop(self):
        self._shutting_down = True
        for t in self._bg:
            t.cancel()
        for w in list(self.workers.values()):
            self._kill_worker_proc(w)
        await self.server.stop()
        if self.gcs is not None:
            await self.gcs.close()
        for c in self._peer_conns.values():
            await c.close()
        self.store.shutdown()

    # ------------------------------------------------------------- pubsub in
    async def _on_publish(self, conn, msg):
        channel, data = msg["channel"], msg["data"]
        if channel == "resource_view":
            version = data.get("version")
            view = self.cluster_view.get(data["node_id"])
            if view is not None:
                last = view.get("view_version")
                if version is not None and last is not None and \
                        version <= last:
                    return  # stale/reordered delta: versions apply monotonically
                view["available"] = data["available"]
                view["total"] = data["total"]
                if version is not None:
                    view["view_version"] = version
            else:
                self.cluster_view[data["node_id"]] = {
                    "node_id": data["node_id"], "available": data["available"],
                    "total": data["total"], "addr": None, "alive": True,
                    "view_version": version,
                }
            self._pump_queued_leases()
        elif channel == "node":
            node = msg["data"]["node"]
            if msg["data"]["event"] == "added":
                self.cluster_view[node["node_id"]] = node
            else:
                self.cluster_view.pop(node["node_id"], None)

    # ---------------------------------------------------------- gcs reports
    async def _report_loop(self):
        interval = RayConfig.heartbeat_interval_ms / 1000.0
        # Versioned resource view (reference: ray_syncer.proto:62 versioned
        # snapshots): the version bumps ONLY when the view changes, so the
        # GCS can skip rebroadcasting unchanged reports — steady-state sync
        # traffic drops to liveness pings instead of O(nodes^2) view spam.
        view_version = 0
        last_fingerprint = None
        while True:
            await asyncio.sleep(interval)
            try:
                # Pending demand: resource shapes of leases queued behind
                # busy capacity — the autoscaler's scale-up signal
                # (reference: ResourceLoad in the raylet's report).
                demand = [dict(res) for res, _b, f in self._queued_leases
                          if not f.done()]
                cutoff = time.monotonic() - 5.0
                for shape in list(self._infeasible_demand):
                    ts, res, _w = self._infeasible_demand[shape]
                    if ts < cutoff:
                        del self._infeasible_demand[shape]
                    else:
                        demand.append(dict(res))
                self._update_builtin_metrics()
                # Zero-resource actors (num_cpus=0 queues, Serve replicas)
                # don't show up in resource accounting, so the autoscaler
                # must not infer idleness from available==total alone.
                busy = sum(1 for w in self.workers.values()
                           if w.state == "leased"
                           or (w.is_actor and w.state != "dead"))
                # fingerprint covers ONLY the broadcast payload
                # (available/total): demand and busy-count ride every report
                # regardless, and versioning them would rebroadcast identical
                # views on queue churn
                fingerprint = (tuple(sorted(self.resources_available.items())),
                               tuple(sorted(self.resources_total.items())))
                if fingerprint != last_fingerprint:
                    view_version += 1
                    last_fingerprint = fingerprint
                from ray_tpu._private import profiler

                if profiler.SAMPLING:
                    delta = profiler.take_delta()
                    if delta:
                        await self.gcs.notify("profile_push", {
                            "node_id": self.node_id.hex(),
                            "entries": delta})
                resp = await self.gcs.call("resource_report", {
                    "node_id": self.node_id.binary(),
                    "available": self.resources_available,
                    "total": self.resources_total,
                    "pending_demand": demand,
                    "busy_workers": busy,
                    "version": view_version,
                }, timeout=RayConfig.gcs_rpc_timeout_s)
                if resp.get("dead"):
                    logger.error("GCS declared this node dead; exiting")
                    os._exit(1)
                if resp.get("unknown") and not self._gcs_reconnecting:
                    # A restarted GCS hasn't seen us: re-register in place.
                    self._gcs_reconnecting = True
                    try:
                        await self._connect_gcs()
                        logger.info("nodelet %s re-registered after GCS "
                                    "restart", self.node_id.hex()[:8])
                    except (ConnectionError, OSError, asyncio.TimeoutError):
                        pass
                    finally:
                        self._gcs_reconnecting = False
            except (ConnectionError, asyncio.TimeoutError):
                logger.warning("GCS unreachable from nodelet %s", self.node_id.hex()[:8])

    def _update_builtin_metrics(self):
        """Node-level gauges (reference: metric_defs.cc canonical metrics)."""
        from ray_tpu._private import metrics as M

        if not hasattr(self, "_m_resources"):
            self._m_resources = M.Gauge(
                "node_resources_available", "available per resource")
            self._m_resources_total = M.Gauge(
                "node_resources_total", "total per resource")
            self._m_workers = M.Gauge("node_workers", "worker processes")
            self._m_store_bytes = M.Gauge(
                "object_store_bytes_used", "plasma bytes in use")
            self._m_store_objects = M.Gauge(
                "object_store_objects", "local objects")
            self._m_store_capacity = M.Gauge(
                "object_store_capacity_bytes", "plasma capacity")
            self._m_store_arena = M.Gauge(
                "object_store_arena_bytes",
                "pre-faulted arena slab bytes (live + leased + free)")
            self._m_mem_used = M.Gauge(
                "node_mem_used_bytes", "host memory in use")
            self._m_mem_total = M.Gauge(
                "node_mem_total_bytes", "host memory total")
        nid = self.node_id.hex()[:12]
        for k, v in self.resources_available.items():
            self._m_resources.set(v, {"node": nid, "resource": k})
        for k, v in self.resources_total.items():
            self._m_resources_total.set(v, {"node": nid, "resource": k})
        self._m_workers.set(
            sum(1 for w in self.workers.values() if w.state != "dead"),
            {"node": nid})
        st = self.store.stats()
        self._m_store_bytes.set(st.get("used", 0), {"node": nid})
        self._m_store_objects.set(st.get("num_objects", len(self.store.objects)),
                                  {"node": nid})
        self._m_store_capacity.set(self.store.capacity, {"node": nid})
        self._m_store_arena.set(st.get("arena_bytes", 0), {"node": nid})
        from ray_tpu._private.memory_monitor import _read_meminfo

        mem = _read_meminfo()
        if mem is not None:
            self._m_mem_used.set(mem[0], {"node": nid})
            self._m_mem_total.set(mem[1], {"node": nid})

    async def rpc_metrics_push(self, conn, msg):
        """A worker pushes its metric snapshot for this node's scrape
        endpoint (reference: core-worker -> metrics agent export)."""
        self.metrics_registry.merge_pushed(msg["source"], msg["snapshot"])
        profile = msg.get("profile")
        if profile:
            # piggybacked profiler delta: forward to the GCS aggregate (the
            # nodelet only relays — cluster-wide merging happens once)
            try:
                await self.gcs.notify("profile_push", {
                    "node_id": self.node_id.hex(), "entries": profile})
            except (ConnectionError, rpc.ConnectionLost):
                pass  # observability must never fail the push path
        return True

    async def rpc_get_metrics_text(self, conn, msg):
        return self.metrics_registry.prometheus_text()

    # --------------------------------------------------------- disk monitor
    def _disk_usage_fraction(self) -> Optional[float]:
        """Fraction of the session-dir filesystem in use (test hook:
        RAY_TPU_FAKE_DISK_USAGE)."""
        fake = os.environ.get("RAY_TPU_FAKE_DISK_USAGE")
        if fake:
            try:
                return float(fake)
            except ValueError:
                pass
        try:
            st = os.statvfs(self.session_dir)
        except OSError:
            return None
        total = st.f_blocks * st.f_frsize
        if total <= 0:
            return None
        return 1.0 - (st.f_bavail * st.f_frsize) / total

    async def _fs_monitor_loop(self):
        """Reject new work while the local filesystem is nearly full
        (reference: _private/utils FileSystemMonitor + raylet's
        over-capacity rejection): a full disk fails spills, log writes, and
        runtime-env installs in ways that masquerade as unrelated bugs —
        better to stop taking leases and say why."""
        while True:
            frac = self._disk_usage_fraction()
            threshold = RayConfig.local_fs_capacity_threshold
            over = frac is not None and frac >= threshold
            if over and not self._disk_full:
                logger.warning(
                    "local filesystem is %.1f%% full (threshold %.0f%%): "
                    "this node stops accepting new leases until space "
                    "frees up", frac * 100, threshold * 100)
            elif self._disk_full and not over:
                logger.info("local filesystem back under the capacity "
                            "threshold; accepting leases again")
            self._disk_full = over
            await asyncio.sleep(RayConfig.fs_monitor_interval_s)

    # ------------------------------------------------------------- log files
    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    async def rpc_list_workers(self, conn, msg):
        """This node's worker processes (reference: util/state list_workers
        — worker id, pid, state, actor binding, env pool, uptime)."""
        now = time.monotonic()
        out = []
        for w in self.workers.values():
            out.append({
                "worker_id": w.worker_id.hex() if hasattr(w.worker_id, "hex")
                else bytes(w.worker_id).hex(),
                "pid": w.pid,
                "state": w.state,
                "is_actor": w.is_actor,
                "actor_id": w.actor_id.hex() if w.actor_id else None,
                "env_key": w.env_key,
                "uptime_s": round(now - w.started_at, 1),
            })
        return out

    async def rpc_list_log_files(self, conn, msg):
        """Names + sizes of this node's log files (worker stdout/stderr,
        nodelet/gcs logs) — the `ray logs` surface (reference:
        python/ray/_private/log_monitor.py; dashboard log module)."""
        log_dir = self._log_dir()
        out = []
        try:
            names = sorted(os.listdir(log_dir))
        except FileNotFoundError:
            return out
        for name in names:
            path = os.path.join(log_dir, name)
            try:
                if not os.path.isfile(path):
                    continue
                st = os.stat(path)
            except FileNotFoundError:
                continue  # rotated/unlinked between listdir and stat
            out.append({"name": name, "size": st.st_size,
                        "mtime": st.st_mtime})
        return out

    async def rpc_tail_log(self, conn, msg):
        """Last ``nbytes`` of one log file.  The name is sanitized to a
        basename inside the session logs dir — no path traversal."""
        name = os.path.basename(msg["name"])
        path = os.path.join(self._log_dir(), name)
        nbytes = min(int(msg.get("nbytes", 64 * 1024)), 4 * 1024 * 1024)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read()
        except FileNotFoundError:
            return None

    # ------------------------------------------------- stacks / hang watchdog
    def _live_worker_conns(self) -> List[WorkerHandle]:
        return [w for w in self.workers.values()
                if w.conn is not None and not w.conn.closed
                and w.state not in ("starting", "dead")]

    async def rpc_rpc_stats(self, conn, msg):
        """Per-method served-RPC counters over this nodelet's live
        connections ({method: {count, total_s}}); `ray_tpu summary rpc`
        cross-checks the observed names against the static wire contract."""
        agg: Dict[str, list] = {}
        for c in self.server.connections:
            for method, (count, total_s) in c.handler_stats().items():
                st = agg.setdefault(method, [0, 0.0])
                st[0] += count
                st[1] += total_s
        return {m: {"count": v[0], "total_s": v[1]}
                for m, v in agg.items()}

    async def rpc_dump_stacks(self, conn, msg):
        """Fan `dump_stacks` out to every registered worker on this node and
        capture the nodelet's own threads (the `ray_tpu stack` node payload;
        reference: `ray stack` shells out to py-spy per process — here each
        process samples itself via sys._current_frames()).  ``task_id``
        narrows the reply to workers currently executing that task."""
        from ray_tpu._private.introspect import capture_thread_stacks

        msg = msg or {}
        task_id = msg.get("task_id")

        async def one(w: WorkerHandle):
            try:
                return await w.conn.call("dump_stacks", None, timeout=10)
            except (ConnectionError, rpc.ConnectionLost,
                    asyncio.TimeoutError):
                return None

        dumps = await asyncio.gather(*(one(w)
                                       for w in self._live_worker_conns()))
        workers = [d for d in dumps if d is not None]
        if task_id:
            workers = [d for d in workers
                       if any(t["task_id"].startswith(task_id)
                              for t in d.get("running_tasks", []))]
        out = {"node_id": self.node_id.hex(), "addr": list(self.addr),
               "workers": workers}
        if not task_id:
            out["nodelet"] = {"kind": "nodelet", "pid": os.getpid(),
                              "threads": capture_thread_stacks(),
                              "running_tasks": []}
        return out

    @staticmethod
    def _env_float(name: str, default: float) -> float:
        """Live env override (read per tick, unlike RayConfig's first-read
        cache) so tests and operators can retune the watchdog on a running
        node via the set_env hook / environment."""
        raw = os.environ.get(name)
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
        return default

    async def _hang_watchdog_loop(self):
        """Flag tasks running suspiciously long (reference: the dashboard's
        hanging-task diagnosis from task events).  Each tick polls every
        busy worker's running tasks; a task is suspected hung past
        max(hang_p95_multiplier x its name's recent exec p95,
        hang_p95_floor_s), or past the absolute RAY_TPU_HANG_THRESHOLD_S
        when no history exists.  First flag attaches a one-shot stack dump
        and rides the task-event pipeline; the ray_tpu_suspected_hung_tasks
        gauge tracks the live count."""
        from ray_tpu._private import metrics as M

        m_hung = M.Gauge("suspected_hung_tasks",
                         "running tasks past their hang threshold, per node")
        nid = self.node_id.hex()[:12]
        while True:
            interval = self._env_float("RAY_TPU_HANG_WATCHDOG_INTERVAL_S",
                                       RayConfig.hang_watchdog_interval_s)
            if interval <= 0:
                await asyncio.sleep(2.0)
                continue
            await asyncio.sleep(interval)
            try:
                await self._hang_watchdog_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("hang watchdog tick failed")
            m_hung.set(len(self._suspected_hung), {"node": nid})

    async def _hang_watchdog_tick(self):
        threshold = self._env_float("RAY_TPU_HANG_THRESHOLD_S",
                                    RayConfig.hang_threshold_s)
        mult = RayConfig.hang_p95_multiplier
        floor = RayConfig.hang_p95_floor_s
        min_samples = RayConfig.hang_min_samples
        events = []
        seen: Set[Tuple[str, int]] = set()
        for w in self._live_worker_conns():
            try:
                tasks = await w.conn.call("get_running_tasks", None,
                                          timeout=10)
            except (ConnectionError, rpc.ConnectionLost,
                    asyncio.TimeoutError):
                continue
            for t in tasks:
                key = (t["task_id"], t.get("attempt", 0))
                seen.add(key)
                p95, samples = t.get("p95_s"), t.get("samples", 0)
                elapsed = t["elapsed_s"]
                limit = threshold
                if p95 is not None and samples >= min_samples:
                    limit = min(limit, max(mult * p95, floor))
                if elapsed <= limit or key in self._suspected_hung:
                    continue
                stack = await self._task_stack(w, t["task_id"])
                self._suspected_hung[key] = {
                    "worker_id": w.worker_id.hex(), "flagged_at": time.time()}
                logger.warning(
                    "task %s (%s) has been running %.1fs (threshold %.1fs): "
                    "suspected hung; stack attached to its task row",
                    t["task_id"][:16], t["name"], elapsed, limit)
                events.append({
                    "task_id": t["task_id"], "attempt": t.get("attempt", 0),
                    "name": t["name"], "state": "HUNG", "ts": time.time(),
                    "node_id": self.node_id.hex(),
                    "worker_id": w.worker_id.hex(),
                    "elapsed_s": round(elapsed, 3),
                    "threshold_s": round(limit, 3),
                    "stack": stack,
                })
        # a flagged task that stopped running (finished/failed/worker died)
        # clears here; its terminal lifecycle event clears the state fold
        for key in [k for k in self._suspected_hung if k not in seen]:
            del self._suspected_hung[key]
        if events:
            try:
                await self.gcs.notify("add_task_events", {"events": events})
            except ConnectionError:
                pass
            # One-shot hung stacks join the cluster flamegraph too (tagged
            # 'hung' at render time) — a hung task shows up in the profile
            # even when continuous sampling is off, not only in /api/hangs.
            from ray_tpu._private.profiler import fold_formatted_stack

            entries = [
                [ev["name"] or "", "core",
                 fold_formatted_stack(ev["stack"]), 1, "hung"]
                for ev in events if ev.get("stack")]
            if entries:
                try:
                    await self.gcs.notify("profile_push", {
                        "node_id": self.node_id.hex(), "entries": entries})
                except ConnectionError:
                    pass

    async def _task_stack(self, w: WorkerHandle, task_id: str):
        """One-shot stack dump of the worker, reduced to the executing
        task's thread (whole-process dump as fallback for async tasks)."""
        try:
            dump = await w.conn.call("dump_stacks", None, timeout=10)
        except (ConnectionError, rpc.ConnectionLost, asyncio.TimeoutError):
            return None
        for t in dump.get("threads", []):
            if t.get("task_id") == task_id:
                return t["stack"]
        from ray_tpu._private.introspect import format_stack_payload

        return format_stack_payload(dump)

    async def _flush_dir_loop(self):
        while True:
            await asyncio.sleep(0.05)
            if self._dir_added:
                batch, self._dir_added = self._dir_added, []
                try:
                    await self.gcs.notify("object_locations_added",
                                          {"node_id": self.node_id.binary(), "oids": batch})
                except ConnectionError:
                    pass
            if self._dir_removed:
                batch, self._dir_removed = self._dir_removed, []
                try:
                    await self.gcs.notify("object_locations_removed",
                                          {"node_id": self.node_id.binary(), "oids": batch})
                except ConnectionError:
                    pass

    def _on_object_sealed(self, oid: ObjectID, size: int):
        self._dir_added.append(oid.binary())

    def _on_object_deleted(self, oid: ObjectID):
        self._dir_removed.append(oid.binary())

    # -------------------------------------------------------- object transfer
    def _on_store_miss(self, oid: ObjectID):
        if oid in self._pulls_inflight:
            return
        self._pulls_inflight.add(oid)
        asyncio.get_event_loop().create_task(self._pull(oid))

    async def _pull(self, oid: ObjectID):
        """Pull one object from any remote holder (reference: PullManager +
        chunked push, object_manager.proto:61; pull-retries until a holder appears)."""
        try:
            delay = 0.05
            while not self.store.contains(oid):
                if self._shutting_down:
                    return
                try:
                    locs = await self.gcs.call("get_object_locations", {"oids": [oid.binary()]})
                except ConnectionError:
                    return
                addrs = [tuple(a) for a in locs.get(oid.binary(), [])]
                addrs = [a for a in addrs if a != self.addr]
                fetched = False
                for addr in addrs:
                    if await self._fetch_from(addr, oid):
                        fetched = True
                        break
                if fetched:
                    break
                # No holder yet: the object may still be being produced; waiters
                # are resolved by seal (local production) or a later pull round.
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
            for fut in self.waiters.pop(oid, []):
                if not fut.done():
                    fut.set_result(True)
        finally:
            self._pulls_inflight.discard(oid)

    async def _peer(self, addr: Tuple[str, int]) -> rpc.Connection:
        conn = self._peer_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(*addr, name=f"nodelet-peer-{addr[1]}")
            self._peer_conns[addr] = conn
        return conn

    async def _fetch_from(self, addr: Tuple[str, int], oid: ObjectID) -> bool:
        """Chunked pull of one object from one holder, with bounded in-flight
        bytes (reference: PullManager admission pull_manager.h:52, chunked
        transfer object_manager.proto:61).  A multi-GiB object never becomes
        one giant RPC frame; chunks land directly in the pre-allocated local
        segment."""
        chunk = RayConfig.fetch_chunk_bytes
        timeout = RayConfig.gcs_rpc_timeout_s
        try:
            conn = await self._peer(addr)
            # the first chunk also carries the total size, so sub-chunk
            # objects (the common case) complete in ONE round trip
            first = await conn.call(
                "fetch_object_chunk",
                {"oid": oid.binary(), "off": 0, "len": chunk},
                timeout=timeout)
            if first is None:
                return False
            size = first["size"]
            if size <= chunk:
                self.store.write_and_seal(oid, memoryview(first["data"]),
                                          is_primary=False)
                return True
            try:
                self.store.create(oid, size, is_primary=False)
            except FileExistsError:
                return self.store.contains(oid)  # sealed locally mid-pull
            buf = self.store.write_buffer(oid)
            buf[0:len(first["data"])] = first["data"]
            sem = asyncio.Semaphore(
                max(RayConfig.object_transfer_inflight_bytes // chunk, 1))
            failed = False

            async def fetch_chunk(off: int):
                nonlocal failed
                async with sem:
                    if failed:
                        return
                    try:
                        resp = await conn.call(
                            "fetch_object_chunk",
                            {"oid": oid.binary(), "off": off,
                             "len": min(chunk, size - off)},
                            timeout=timeout)
                    except (ConnectionError, asyncio.TimeoutError):
                        failed = True
                        return
                    if resp is None:  # holder evicted it mid-transfer
                        failed = True
                        return
                    buf[off:off + len(resp["data"])] = resp["data"]

            await asyncio.gather(
                *[fetch_chunk(off) for off in range(chunk, size, chunk)])
            if failed:
                self.store.abort(oid)
                return False
            try:
                self.store.seal(oid)
            except KeyError:
                return False  # freed mid-transfer; caller re-loops
            return True
        except (ConnectionError, asyncio.TimeoutError, ObjectStoreFullError):
            self.store.abort(oid)
            return False

    async def rpc_fetch_object_chunk(self, conn, msg):
        mv = self.store.read_bytes(ObjectID(msg["oid"]))
        if mv is None:
            return None
        off, ln = msg["off"], msg["len"]
        # bytes() copy: bounded by the chunk size, and decouples the send
        # from store eviction.
        return {"size": mv.nbytes, "data": bytes(mv[off:off + ln])}

    async def rpc_free_local_objects(self, conn, msg):
        for b in msg["oids"]:
            self.store.delete(ObjectID(b))
        return True

    # ------------------------------------------------------------ worker pool
    def _spawn_worker(self, env_key: str = "") -> WorkerHandle:
        worker_id = WorkerID.from_random()
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"worker-{worker_id.hex()[:8]}.out"), "ab")
        env = dict(os.environ)
        env.update(RayConfig.overrides_as_env())
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        launch = self._env_launch.get(env_key) if env_key else None
        python = sys.executable
        if launch is not None and launch.get("python"):
            # venv worker: the framework itself must stay importable from
            # the venv interpreter (--system-site-packages covers installed
            # deps; PYTHONPATH covers a source checkout)
            python = launch["python"]
            repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            env["PYTHONPATH"] = repo_root + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        cmd = [
            python, "-u", "-m", "ray_tpu._private.worker_main",
            "--nodelet-host", self.addr[0], "--nodelet-port", str(self.addr[1]),
            "--gcs-host", self.gcs_addr[0], "--gcs-port", str(self.gcs_addr[1]),
            "--worker-id", worker_id.hex(),
            "--node-id", self.node_id.hex(),
            "--session-dir", self.session_dir,
        ]
        if launch is not None and launch.get("image"):
            from ray_tpu.runtime_env.container import wrap_worker_command

            cmd, extra_env = wrap_worker_command(
                launch["image"], cmd, env, self.session_dir,
                launch.get("image_args"))
            env.update(extra_env)
        proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env,
                                cwd=os.getcwd())
        out.close()
        h = WorkerHandle(worker_id.binary(), proc, env_key=env_key)
        self.workers[worker_id.binary()] = h
        self._starting_count += 1
        if env_key:
            self._starting_by_key[env_key] = \
                self._starting_by_key.get(env_key, 0) + 1
        return h

    async def rpc_register_worker(self, conn, msg):
        wid = msg["worker_id"]
        h = self.workers.get(wid)
        if h is None:
            # A worker we didn't spawn (e.g. driver connecting as a client).
            return {"ok": True, "driver": True}
        h.conn = conn
        h.addr = tuple(msg["addr"])
        # the worker's self-reported pid wins over the spawner's proc.pid:
        # under a pid namespace the two differ, and the self-reported one is
        # what appears in the worker's own logs and flight-recorder records
        h.pid = msg.get("pid", h.pid)
        h.state = "idle"
        h.idle_since = time.monotonic()
        self._starting_count = max(0, self._starting_count - 1)
        if h.env_key:
            self._starting_by_key[h.env_key] = max(
                0, self._starting_by_key.get(h.env_key, 0) - 1)
        conn.context["worker_id"] = wid
        self._fulfill_pops()
        return {"ok": True}

    def _idle_workers(self, env_key: str = "") -> List[WorkerHandle]:
        return [w for w in self.workers.values()
                if w.state == "idle" and w.env_key == env_key]

    def _fulfill_pops(self):
        # match waiters to idle workers of the SAME env pool; leave
        # unmatched waiters queued (their pool's worker is still booting)
        unmatched: deque = deque()
        while self._pop_queue:
            fut, env_key = self._pop_queue.popleft()
            if fut.done():
                continue
            idle = self._idle_workers(env_key)
            if not idle:
                unmatched.append((fut, env_key))
                continue
            w = idle[0]
            w.state = "leased"
            w.leased_since = time.monotonic()
            fut.set_result(w)
        self._pop_queue = unmatched
        # Maintain pipeline: spawn if LIVE demand outstrips starting workers —
        # cancelled pops (done futures) must not trigger spawns, or a drained
        # burst leaves a late wave of workers booting (pure CPU theft on small
        # hosts) with no tasks to run.  Deficits are per env pool: a venv
        # waiter is never satisfied by a default-pool boot.
        live_by_key: Dict[str, int] = {}
        for f, k in self._pop_queue:
            if not f.done():
                live_by_key[k] = live_by_key.get(k, 0) + 1
        budget = RayConfig.maximum_startup_concurrency - self._starting_count
        for k, live in live_by_key.items():
            starting = self._starting_by_key.get(k, 0) if k else (
                self._starting_count
                - sum(self._starting_by_key.values()))
            deficit = live - starting
            for _ in range(min(max(deficit, 0), max(budget, 0))):
                self._spawn_worker(k)
                budget -= 1

    async def _pop_worker(self, token: Optional[str] = None,
                          env_key: str = "") -> WorkerHandle:
        idle = self._idle_workers(env_key)
        if idle:
            w = idle[0]
            w.state = "leased"
            w.leased_since = time.monotonic()
            return w
        fut = asyncio.get_event_loop().create_future()
        self._pop_queue.append((fut, env_key))
        if token:
            self._lease_waiters[token] = fut
        starting_here = self._starting_by_key.get(env_key, 0) if env_key \
            else self._starting_count - sum(self._starting_by_key.values())
        if self._starting_count < RayConfig.maximum_startup_concurrency \
                or (env_key and starting_here == 0):
            self._spawn_worker(env_key)
        try:
            return await fut
        finally:
            if token:
                self._lease_waiters.pop(token, None)

    async def _prepare_env(self, env_key: str, runtime_env: dict) -> None:
        """Resolve an isolation env (pip venv build / container image) into
        launch adjustments, cached per env_key.  Runs in the env thread pool
        so a venv build never blocks the event loop — the nodelet plays the
        reference runtime-env agent's role in-process (reference:
        runtime_env/agent/runtime_env_agent.py GetOrCreateRuntimeEnv)."""
        if env_key in self._env_launch:
            return
        from ray_tpu import runtime_env as renv_mod

        launch = await asyncio.get_event_loop().run_in_executor(
            self._env_pool, renv_mod.prepare_worker_launch,
            runtime_env, self.session_dir)
        self._env_launch[env_key] = launch or {}

    async def rpc_cancel_lease_requests(self, conn, msg):
        """Client gave up on outstanding lease requests (its task queue
        drained); resolve their waits so no worker is spawned/held for them."""
        cancelled = 0
        for token in msg.get("tokens", ()):
            fut = self._lease_waiters.pop(token, None)
            if fut is not None and not fut.done():
                fut.set_exception(_LeaseCancelled())
                cancelled += 1
        await self._reap_surplus_starting()
        return {"cancelled": cancelled}

    async def _reap_surplus_starting(self) -> None:
        """With no live demand, kill workers still BOOTING: a Python worker
        costs ~2 s of pure CPU to start, and on small hosts a wave of
        no-longer-needed boots visibly steals the cores from whatever runs
        next.  Booted (idle) workers are kept — they are already paid for."""
        if any(not f.done() for f, _k in self._pop_queue):
            return
        # leases queued on resources will need workers the moment capacity
        # frees — their boots are not surplus
        if any(not f.done() for _, _, f in self._queued_leases):
            return
        for w in list(self.workers.values()):
            if w.state == "starting" and w.proc is not None:
                self._kill_worker_proc(w)
                # intentional reap, not a crash: no GCS worker_died report
                await self._handle_worker_death(w, "surplus boot reaped",
                                                report=False)

    async def _monitor_workers_loop(self):
        from ray_tpu._private.memory_monitor import MemoryMonitor

        mm = MemoryMonitor(RayConfig.memory_usage_threshold) \
            if RayConfig.memory_monitor_refresh_ms > 0 else None
        last_mm_check = 0.0
        while True:
            await asyncio.sleep(0.2)
            # refresh each tick so a schedule armed at runtime (rpc_set_env
            # test hook) takes effect live; unchanged schedules cost one env
            # read + string compare
            fault_injection.refresh()
            if fault_injection.ENABLED and fault_injection.hit(
                    "nodelet.tick", detail=self.node_id.hex()) == "kill":
                fault_injection.kill_self()
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None and w.state != "dead":
                    await self._handle_worker_death(w, f"exit code {w.proc.returncode}")
            # Reap long-idle workers.
            now = time.monotonic()
            reap_after = RayConfig.idle_worker_killing_time_ms / 1000.0
            for w in list(self.workers.values()):
                if w.state == "idle" and now - w.idle_since > reap_after:
                    self._kill_worker_proc(w)
                    await self._handle_worker_death(w, "idle reaped", report=False)
            # Memory pressure: kill the cheapest-to-retry worker before the
            # kernel OOM-killer shoots something load-bearing (reference:
            # MemoryMonitor + retriable-FIFO worker killing policy).
            if mm is not None and \
                    now - last_mm_check > RayConfig.memory_monitor_refresh_ms / 1000.0:
                last_mm_check = now
                if mm.is_pressured():
                    victim = self._pick_oom_victim()
                    if victim is not None:
                        frac = mm.usage_fraction()
                        logger.warning(
                            "node memory at %.0f%% (threshold %.0f%%): "
                            "killing worker %s to relieve pressure",
                            (frac or 0) * 100,
                            RayConfig.memory_usage_threshold * 100,
                            victim.worker_id.hex()[:8])
                        await self._notify_pressure_kill(victim)
                        self._kill_worker_proc(victim)
                        await self._handle_worker_death(
                            victim, "killed by the memory monitor: node "
                            "memory usage above threshold")

    def _pick_oom_victim(self):
        """Idle workers first (zero work lost), then the task worker with
        the NEWEST lease (least progress lost), actors only as a last resort
        — their state dies with them (reference:
        worker_killing_policy_group_by_owner / _retriable_fifo, approximated:
        the nodelet never sees the task spec, so per-task retriability is
        unknown here — the submitter's retry budget decides what happens
        next)."""
        idle = [w for w in self.workers.values() if w.state == "idle"]
        if idle:
            return idle[0]
        leased = [w for w in self.workers.values()
                  if w.state == "leased" and not w.is_actor]
        if leased:
            return max(leased, key=lambda w: w.leased_since)
        actors = [w for w in self.workers.values()
                  if w.is_actor and w.state != "dead"]
        if actors:
            return max(actors, key=lambda w: w.started_at)
        return None

    async def _handle_worker_death(self, w: WorkerHandle, reason: str, report: bool = True):
        if w.state == "dead":
            return
        prev_state = w.state
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        if prev_state == "starting":
            self._starting_count = max(0, self._starting_count - 1)
            if w.env_key:
                self._starting_by_key[w.env_key] = max(
                    0, self._starting_by_key.get(w.env_key, 0) - 1)
            # A booting worker died (crash or surplus reap).  Live pops may
            # have been counting on it; without a re-pump they would wait
            # forever — nothing else spawns until the next register/return.
            self._fulfill_pops()
        if w.lease_id is not None:
            self._release_lease(w.lease_id)
        # Post-mortem harvest BEFORE reporting: the death notify carries the
        # victim's last recorded moments so the GCS can serve them with the
        # failure instead of them dying with the process.
        blackbox = self._harvest_blackbox(w.worker_id, reason)
        if report and (w.is_actor or prev_state != "idle"):
            try:
                await self.gcs.notify("worker_died", {
                    "worker_id": w.worker_id,
                    "node_id": self.node_id.binary(),
                    "reason": f"worker process died: {reason}",
                    "blackbox": blackbox,
                })
            except ConnectionError:
                pass
        elif blackbox is not None:
            # unreported deaths (idle worker reaped) still archive the ring
            try:
                await self.gcs.notify("blackbox_harvest", {
                    "worker_id": w.worker_id,
                    "node_id": self.node_id.binary(),
                    "blackbox": blackbox,
                })
            except ConnectionError:
                pass

    def _harvest_blackbox(self, worker_id: bytes, reason: str):
        """Read the dead worker's crash-surviving flight-recorder ring out
        of the session dir (the kernel kept the mmap'd pages; SIGKILL could
        not take them), then unlink it — one harvest per death."""
        path = flight_recorder.ring_path(self.session_dir, worker_id.hex())
        records = flight_recorder.harvest(path, limit=200)
        try:
            os.unlink(path)
        except OSError:
            pass
        if not records:
            return None
        if flight_recorder.RECORDING:
            flight_recorder.record(
                "blackbox.harvest",
                f"{worker_id.hex()[:12]}|{len(records)} records")
        return {
            "worker_id": worker_id.hex(),
            "node_id": self.node_id.hex(),
            "harvested_at": time.time(),
            "reason": reason,
            "records": records,
        }

    def _publish_incident(self, rec: dict) -> None:
        gcs = self.gcs
        if gcs is None or gcs.closed:
            return
        try:
            asyncio.get_running_loop().create_task(
                gcs.notify("incident_report", rec))
        except RuntimeError:
            pass  # off-loop close: the local ledger keeps the record

    def _kill_worker_proc(self, w: WorkerHandle):
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.kill()
            except OSError:
                pass

    def _on_conn_lost(self, conn: rpc.Connection):
        from ray_tpu._private.object_store import cleanup_client_connection

        cleanup_client_connection(self.store, conn, waiters=self.waiters)
        # leases granted to a vanished client (driver death, cached leases
        # included): the workers are healthy — return them to the idle pool
        # instead of stranding them in "leased" forever
        for lease_id in conn.context.pop("granted_leases", set()):
            self._release_lease(lease_id)
        wid = conn.context.get("worker_id")
        if wid is not None and not self._shutting_down:
            w = self.workers.get(wid)
            if w is not None:
                asyncio.get_event_loop().create_task(
                    self._handle_worker_death(w, "connection lost"))

    async def rpc_kill_worker(self, conn, msg):
        w = self.workers.get(msg["worker_id"])
        if w is None:
            return False
        self._kill_worker_proc(w)
        await self._handle_worker_death(w, "killed", report=False)
        return True

    # ---------------------------------------------------------- lease broker
    def _record_infeasible_demand(self, resources: Dict[str, float]) -> None:
        """Dedupe one unmet resource shape into the demand view the
        autoscaler reads, warning at most every 30 s per shape (retries come
        every second and must look like one task, not N)."""
        now = time.monotonic()
        shape = tuple(sorted(resources.items()))
        prev = self._infeasible_demand.get(shape)
        warned = prev[2] if prev else 0.0
        if now - warned > 30.0:
            logger.warning(
                "task requiring %s cannot be scheduled on any current "
                "node; it stays pending (an autoscaler may add capacity)",
                resources)
            warned = now
        if len(self._infeasible_demand) < 256 or prev:
            self._infeasible_demand[shape] = (now, dict(resources), warned)

    def _fits_local(self, resources: Dict[str, float], bundle: Optional[Tuple[bytes, int]]) -> bool:
        if bundle is not None:
            b = self.bundles.get(tuple(bundle))
            if b is None:
                return False
            return all(b.available.get(k, 0.0) >= v for k, v in resources.items() if v > 0)
        return all(self.resources_available.get(k, 0.0) >= v
                   for k, v in resources.items() if v > 0)

    def _feasible_local(self, resources: Dict[str, float]) -> bool:
        return all(self.resources_total.get(k, 0.0) >= v for k, v in resources.items() if v > 0)

    def _resolve_bundle(self, bundle, resources: Dict[str, float]):
        """Resolve a lease's bundle key; index -1 means "any bundle of this
        placement group with capacity" (reference: bundle_index=-1 semantics in
        bundle_spec.h — the reference picks any bundle that fits).  Returns
        (concrete_bundle, error_reason)."""
        if bundle is None:
            return None, None
        bundle = (bundle[0], bundle[1])
        if bundle[1] >= 0:
            if bundle not in self.bundles:
                return None, "unknown placement bundle"
            return bundle, None
        cands = sorted(k for k in self.bundles if k[0] == bundle[0])
        if not cands:
            return None, "no bundle of this placement group on this node"
        for k in cands:
            if self._fits_local(resources, k):
                return k, None
        # All busy now — but only queue on a bundle whose TOTAL can ever fit;
        # a request exceeding every bundle's capacity must error, not hang.
        for k in cands:
            total = self.bundles[k].resources
            if all(total.get(rk, 0.0) >= v
                   for rk, v in resources.items() if v > 0):
                return k, None
        return None, "request exceeds every bundle's total resources"

    def _acquire(self, resources: Dict[str, float], bundle) -> None:
        if bundle is not None:
            b = self.bundles[tuple(bundle)]
            for k, v in resources.items():
                b.available[k] = b.available.get(k, 0.0) - v
        else:
            for k, v in resources.items():
                self.resources_available[k] = self.resources_available.get(k, 0.0) - v

    def _release(self, resources: Dict[str, float], bundle) -> None:
        if bundle is not None:
            b = self.bundles.get(tuple(bundle))
            if b is None:
                return
            for k, v in resources.items():
                b.available[k] = min(b.available.get(k, 0.0) + v, b.resources.get(k, 0.0))
        else:
            for k, v in resources.items():
                self.resources_available[k] = min(
                    self.resources_available.get(k, 0.0) + v, self.resources_total.get(k, 0.0))

    def _pick_node(self, resources: Dict[str, float], strategy: dict) -> Optional[bytes]:
        """Cluster-level node choice (reference: ClusterResourceScheduler +
        hybrid/spread policies, hybrid_scheduling_policy.h:50)."""
        my_id = self.node_id.binary()
        feasible = []
        for nid, view in self.cluster_view.items():
            total = view.get("total", {})
            if all(total.get(k, 0.0) >= v for k, v in resources.items() if v > 0):
                avail = view.get("available", {}) if nid != my_id else self.resources_available
                has_now = all(avail.get(k, 0.0) >= v for k, v in resources.items() if v > 0)
                feasible.append((nid, view, has_now))
        if not feasible:
            return None
        kind = strategy.get("kind", "default")
        if kind == "node_label":
            # label policy (reference: NodeLabelSchedulingStrategy,
            # node_label_scheduling_policy.h): hard selectors filter,
            # soft selectors rank; resources break ties via readiness
            sel = strategy.get("label_selector") or {}
            hard = sel.get("hard") or {}
            soft = sel.get("soft") or {}

            def labels_of(f):
                nid, view, _ = f
                return self.labels if nid == my_id \
                    else (view.get("labels") or {})

            if hard:
                feasible = [f for f in feasible if all(
                    labels_of(f).get(k) == v for k, v in hard.items())]
                if not feasible:
                    return None  # no labeled node: stays pending demand
            pool = [f for f in feasible if f[2]] or feasible
            if soft:
                pool.sort(key=lambda f: -sum(
                    labels_of(f).get(k) == v for k, v in soft.items()))
            return pool[0][0]
        ready = [f for f in feasible if f[2]]
        # Score by the REQUESTED resource shape, not CPU alone: a TPU-saturated
        # node must not look idle to a TPU task just because its CPUs are free
        # (reference: LeastResourceScorer scores the demanded resources,
        # scorer.h:41).
        req_keys = [k for k, v in resources.items() if v > 0] or ["CPU"]
        if kind == "spread":
            # Prefer ready nodes, most headroom for this request first.
            pool = ready or feasible
            def load_key(f):
                nid, view, _ = f
                avail = view.get("available", {}) if nid != my_id else self.resources_available
                return -min(avail.get(k, 0.0) / max(resources.get(k, 1.0), 1e-9)
                            for k in req_keys)
            pool.sort(key=load_key)
            return pool[0][0]
        # hybrid default: prefer local while it has capacity, else first ready
        # node, else queue locally (return my_id with no capacity -> queued).
        if self._fits_local(resources, None) or not ready:
            return my_id
        local_util = max(
            1.0 - (self.resources_available.get(k, 0.0)
                   / max(self.resources_total.get(k, 1e-9), 1e-9))
            for k in req_keys)
        if local_util < RayConfig.scheduler_spread_threshold and self._feasible_local(resources):
            return my_id
        return ready[0][0]

    async def rpc_request_worker_lease(self, conn, msg):
        """Grant a worker lease, spill to a better node, or queue.

        Reply: {type: granted, lease_id, worker_addr, worker_id}
             | {type: spillback, node_addr}
             | {type: infeasible}
        (reference: NodeManager::HandleRequestWorkerLease node_manager.cc:1794)
        """
        t_req = time.monotonic()
        resources = msg.get("resources", {})
        strategy = msg.get("strategy", {})
        bundle = msg.get("bundle")
        spillback_count = msg.get("spillback_count", 0)
        if self._disk_full:
            # a nearly-full local filesystem fails spills/logs/runtime-envs
            # in confusing ways — push work AWAY: spill to a healthy node
            # when one exists, bounce a retry otherwise (reference:
            # FileSystemMonitor over-capacity rejection).  A plain retry
            # here would pin the task to this node forever: the client's
            # retry path re-picks its preferred node.
            if bundle is None and strategy.get("kind") != "node_affinity":
                target = self._pick_node(resources, strategy)
                if target is not None and target != self.node_id.binary():
                    view = self.cluster_view.get(target)
                    if view and view.get("addr"):
                        return {"type": "spillback",
                                "node_addr": view["addr"]}
            return {"type": "retry", "delay": 2.0,
                    "reason": "node local filesystem is over the capacity "
                              "threshold"}
        if bundle is not None:
            bundle, err = self._resolve_bundle(bundle, resources)
            if err is not None:
                return {"type": "infeasible", "reason": err}
        elif strategy.get("kind") not in ("node_affinity",):
            # Spilled requests grant locally when they fit (no pointless
            # extra hops: the sender already chose this node); they re-spill
            # only while they DON'T fit here, up to a bounded chain
            # (reference: grant_or_reject spillback leases,
            # node_manager.cc:1794 — the cap replaces reject-and-retry;
            # the previous hard `< 2` cap could also queue a spilled
            # request forever on a node where it is locally infeasible).
            local_fit = self._fits_local(resources, None)
            consult = spillback_count == 0 or not local_fit
            max_spill = RayConfig.max_lease_spillbacks
            target = self._pick_node(resources, strategy) if consult else None
            if consult and target is None:
                if strategy.get("kind") == "node_label":
                    # resources may fit HERE, but a hard label selector that
                    # matched no node must never fall through to a local
                    # grant on a non-matching node.  NOT recorded as
                    # resource demand: the autoscaler would provision
                    # generic capacity that still lacks the label.
                    sel = strategy.get("label_selector") or {}
                    now = time.monotonic()
                    if now - getattr(self, "_label_warned", 0.0) > 30.0:
                        self._label_warned = now
                        logger.warning(
                            "task requiring labels %s matches no node; it "
                            "stays pending (label-selector demand is not "
                            "autoscalable)", sel.get("hard"))
                    return {"type": "retry", "delay": 1.0,
                            "reason": "no node matches the label selector"}
                if not self._feasible_local(resources):
                    # No node fits today — but the autoscaler may launch one:
                    # record the unmet shape as demand and have the submitter
                    # retry, keeping the task pending (reference: infeasible
                    # tasks wait; ResourceLoad drives scale-up, with periodic
                    # infeasible-task warnings).
                    self._record_infeasible_demand(resources)
                    return {"type": "retry", "delay": 1.0,
                            "reason": f"no node currently satisfies {resources}"}
            elif target is not None and target != self.node_id.binary() \
                    and spillback_count < max_spill:
                view = self.cluster_view.get(target)
                if view and view.get("addr"):
                    return {"type": "spillback", "node_addr": view["addr"]}
            if not local_fit and not self._feasible_local(resources):
                # end of the chain on a node that can NEVER run this shape:
                # bounce to the client rather than queueing forever — and
                # record the shape so demand-driven scale-up still sees it
                self._record_infeasible_demand(resources)
                return {"type": "retry", "delay": 1.0,
                        "reason": f"node cannot ever satisfy {resources}"}
        token = msg.get("token")
        # Local grant (or queue until resources free up).  The pump ACQUIRES on
        # behalf of the waiter before waking it, so concurrent waiters can never
        # be granted against the same capacity.
        if self._fits_local(resources, bundle):
            self._acquire(resources, bundle)
        else:
            fut = asyncio.get_event_loop().create_future()
            self._queued_leases.append((resources, bundle, fut))
            if token:
                self._lease_waiters[token] = fut
            # the capacity we're queueing on may be held by drivers' cached
            # idle leases: ask them to give the warm workers back
            self._hint_lease_reclaim()
            try:
                await fut  # resources are acquired by _pump_queued_leases
            except _LeaseCancelled:
                return {"type": "cancelled"}
            except asyncio.CancelledError:
                if fut.done() and not fut.cancelled():
                    self._release(resources, bundle)
                raise
            finally:
                if token:
                    self._lease_waiters.pop(token, None)
        t_acquired = time.monotonic()
        env_key = msg.get("env_key") or ""
        if env_key:
            try:
                await self._prepare_env(env_key, msg.get("runtime_env") or {})
            except Exception as e:
                logger.warning("runtime env %s setup failed: %r", env_key, e)
                self._release(resources, bundle)
                self._pump_queued_leases()
                return {"type": "env_failed",
                        "reason": f"runtime env setup failed: {e}"}
        try:
            w = await self._pop_worker(token, env_key)
        except _LeaseCancelled:
            self._release(resources, bundle)
            self._pump_queued_leases()  # freed capacity may unblock waiters
            return {"type": "cancelled"}
        except asyncio.CancelledError:
            self._release(resources, bundle)
            self._pump_queued_leases()
            raise
        self._lease_seq += 1
        lease_id = self._lease_seq
        w.lease_id = lease_id
        self.leases[lease_id] = {"resources": resources, "bundle": bundle, "worker": w}
        # remember who holds it: conn loss returns the lease (a dead driver's
        # cached leases must not strand healthy workers in "leased")
        conn.context.setdefault("granted_leases", set()).add(lease_id)
        self._observe_lease_phases(t_req, t_acquired, time.monotonic())
        if flight_recorder.RECORDING:
            flight_recorder.record(
                "lease.grant",
                f"id={lease_id}|worker={w.worker_id.hex()[:12]}")
        return {"type": "granted", "lease_id": lease_id,
                "worker_addr": list(w.addr), "worker_id": w.worker_id}

    def _observe_lease_phases(self, t_req: float, t_acquired: float,
                              t_granted: float) -> None:
        """Lease-grant timing into this node's task_phase_seconds histogram
        (same metric name as the driver/worker phases, so one Prometheus
        query covers the whole chain): lease_queue is time spent waiting for
        resources, worker_pop is env prep + waiting for / booting a worker
        process.  Per lease, not per task — pipelined tasks amortize it."""
        if not hasattr(self, "_m_phase"):
            from ray_tpu._private import metrics as M

            self._m_phase = M.Histogram(
                "task_phase_seconds",
                "task hot-path time per phase (driver submit -> result wake)",
                boundaries=M.PHASE_SECONDS_BOUNDARIES)
        self._m_phase.observe(max(t_acquired - t_req, 0.0),
                              {"phase": "lease_queue"})
        self._m_phase.observe(max(t_granted - t_acquired, 0.0),
                              {"phase": "worker_pop"})

    def _pump_queued_leases(self):
        n = len(self._queued_leases)
        for _ in range(n):
            resources, bundle, fut = self._queued_leases.popleft()
            if fut.done():
                continue
            if self._fits_local(resources, bundle):
                self._acquire(resources, bundle)  # reserve before waking
                fut.set_result(True)
            else:
                self._queued_leases.append((resources, bundle, fut))

    def _release_lease(self, lease_id: int):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        self._release(lease["resources"], lease["bundle"])
        w = lease["worker"]
        if w.state == "leased":
            w.state = "idle"
            w.idle_since = time.monotonic()
            w.lease_id = None
            self._fulfill_pops()
        self._pump_queued_leases()

    async def rpc_return_worker(self, conn, msg):
        conn.context.get("granted_leases", set()).discard(msg["lease_id"])
        self._release_lease(msg["lease_id"])
        return True

    async def _notify_pressure_kill(self, w: WorkerHandle) -> None:
        """Heads-up to the lease holder BEFORE the SIGKILL: the imminent
        'lost' completion is a deliberate pressure kill, not a crash, so
        the submitter retries the task without consuming its crash-retry
        budget (reference: OOM-killed tasks retry on their own counter,
        unlimited by default, so pressure can't exhaust max_retries)."""
        if w.lease_id is None:
            return
        for conn in list(self.server.connections):
            if w.lease_id in conn.context.get("granted_leases", ()):
                try:
                    await conn.notify("pressure_kill",
                                      {"worker_id": w.worker_id})
                except ConnectionError:
                    pass
                return

    # ---------------------------------------------------- reclaim hints
    def _hint_lease_reclaim(self) -> None:
        """Ask clients with cached idle leases to return them: a lease /
        bundle reservation is queued behind resources they hold.  Throttled;
        fire-and-forget over the coalesced batch."""
        now = time.monotonic()
        if now - getattr(self, "_last_lease_hint", 0.0) < 0.5:
            return
        self._last_lease_hint = now
        for conn in list(self.server.connections):
            if conn.context.get("granted_leases"):
                try:
                    conn.notify_coalesced("lease_reclaim", None)
                except ConnectionError:
                    pass

    def _broadcast_extent_reclaim(self) -> None:
        """Store hit full during an extent lease: ask clients to hand back
        idle leased extents before the requester's retry."""
        now = time.monotonic()
        if now - getattr(self, "_last_extent_hint", 0.0) < 0.2:
            return
        self._last_extent_hint = now
        for conn in list(self.server.connections):
            if conn.context.get("plasma_extents"):
                try:
                    conn.notify_coalesced("extent_reclaim", None)
                except ConnectionError:
                    pass

    async def rpc_set_env(self, conn, msg):
        """Fault-injection hook for chaos tests (fake disk usage, fake
        memory pressure): set/clear an env var in THIS nodelet process.
        DISABLED unless RayConfig.test_hooks — an open env-set RPC would
        hand code execution (LD_PRELOAD/PYTHONPATH into spawned workers)
        to anything that can reach the nodelet port."""
        if not RayConfig.test_hooks:
            raise PermissionError("set_env requires RAY_TPU_TEST_HOOKS=1")
        if msg.get("value"):
            os.environ[msg["key"]] = msg["value"]
        else:
            os.environ.pop(msg["key"], None)
        return True

    # ------------------------------------------------------------ actor leases
    async def rpc_lease_worker_for_actor(self, conn, msg):
        """GCS asks this node to host an actor: lease a dedicated worker and run
        the creation task on it (reference: GcsActorScheduler leasing path)."""
        import pickle

        spec = pickle.loads(msg["spec"])
        if self._disk_full:
            # same capacity guard as task leases: a full disk breaks the
            # actor's runtime-env install and log writes
            return {"ok": False, "reason": "node local filesystem is over "
                                           "the capacity threshold"}
        bundle = msg.get("bundle")
        if bundle is not None:
            bundle, err = self._resolve_bundle(bundle, spec.resources)
            if err is not None:
                return {"ok": False, "reason": err}
        if self._fits_local(spec.resources, bundle):
            self._acquire(spec.resources, bundle)
        else:
            if not self._feasible_local(spec.resources) and bundle is None:
                return {"ok": False, "reason": "infeasible"}
            fut = asyncio.get_event_loop().create_future()
            self._queued_leases.append((spec.resources, bundle, fut))
            self._hint_lease_reclaim()
            try:
                await asyncio.wait_for(fut, RayConfig.gcs_rpc_timeout_s * 0.8)
            except asyncio.TimeoutError:
                # wait_for cancelled fut; the pump skips done futures, so the
                # reservation was never made for us.
                return {"ok": False, "reason": "timed out waiting for resources"}
        from ray_tpu import runtime_env as renv_mod

        env_key = renv_mod.env_key(spec.runtime_env)
        if env_key:
            try:
                await self._prepare_env(env_key, spec.runtime_env)
            except Exception as e:
                import pickle

                from ray_tpu.exceptions import RuntimeEnvSetupError

                logger.warning("actor runtime env %s setup failed: %r",
                               env_key, e)
                self._release(spec.resources, bundle)
                self._pump_queued_leases()
                # carry a pickled error: the GCS treats error-bearing
                # replies as deterministic failures (actor marked DEAD)
                # rather than retrying the broken env forever
                return {"ok": False,
                        "reason": f"runtime env setup failed: {e}",
                        "error": pickle.dumps(RuntimeEnvSetupError(  # lint: disable=no-flatten (error record)
                            f"runtime env setup failed: {e}"))}
        w = await self._pop_worker(env_key=env_key)
        self._lease_seq += 1
        w.lease_id = self._lease_seq
        w.is_actor = True
        w.actor_id = spec.actor_creation_id.binary() if spec.actor_creation_id else None
        self.leases[w.lease_id] = {"resources": spec.resources, "bundle": bundle, "worker": w}
        try:
            # No timeout: actor __init__ may legitimately take minutes (model
            # load, jax backend init); worker death surfaces as ConnectionLost.
            reply = await w.conn.call("push_task", msg["spec"], timeout=None)
            if reply.get("status") == "error":
                # Kill the leased process too: _handle_worker_death only
                # untracks it, and an untracked live worker is unreclaimable
                # (reference kills the leased worker when creation fails).
                self._kill_worker_proc(w)
                await self._handle_worker_death(w, "actor constructor raised", report=False)
                return {"ok": False, "reason": "actor constructor raised",
                        "error": reply.get("error")}
        except ConnectionError as e:
            await self._handle_worker_death(w, f"actor creation failed: {e}")
            return {"ok": False, "reason": f"actor creation failed: {e}"}
        return {"ok": True, "worker_addr": list(w.addr), "worker_id": w.worker_id}

    # ------------------------------------------------------- bundles (2PC)
    async def rpc_prepare_bundle(self, conn, msg):
        key = (msg["pg_id"], msg["index"])
        if key in self.bundles:
            return True
        resources = msg["resources"]
        if not all(self.resources_available.get(k, 0.0) >= v
                   for k, v in resources.items() if v > 0):
            # the shortfall may be drivers' cached idle leases: hint, give
            # them one beat to come back, recheck (the GCS retries a failed
            # prepare, so this only shortens the failure window)
            self._hint_lease_reclaim()
            await asyncio.sleep(0.25)
            if not all(self.resources_available.get(k, 0.0) >= v
                       for k, v in resources.items() if v > 0):
                return False
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0.0) - v
        self.bundles[key] = Bundle(msg["pg_id"], msg["index"], resources)
        return True

    async def rpc_commit_bundle(self, conn, msg):
        b = self.bundles.get((msg["pg_id"], msg["index"]))
        if b is None:
            return False
        b.committed = True
        return True

    async def rpc_cancel_bundle(self, conn, msg):
        b = self.bundles.pop((msg["pg_id"], msg["index"]), None)
        if b is None:
            return True
        # Return the bundle's unused reservation to the node pool.
        for k, v in b.resources.items():
            self.resources_available[k] = min(
                self.resources_available.get(k, 0.0) + v, self.resources_total.get(k, 0.0))
        self._pump_queued_leases()
        return True

    # ----------------------------------------------------------------- misc
    async def rpc_node_info(self, conn, msg):
        return {
            "node_id": self.node_id.binary(),
            "addr": list(self.addr),
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": len(self.workers),
            "store": self.store.stats(),
        }


def main(argv=None):
    """Entry point for the nodelet process (reference: raylet/main.cc)."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}",
                        help="JSON node labels for label-selector scheduling")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    parser.add_argument("--node-name", default="")
    args = parser.parse_args(argv)

    import json

    logging.basicConfig(level=logging.INFO, format="[nodelet] %(levelname)s %(message)s")

    async def run():
        import signal

        nodelet = Nodelet(
            (args.gcs_host, args.gcs_port),
            resources=json.loads(args.resources) or None,
            labels=json.loads(args.labels) or None,
            object_store_memory=args.object_store_memory or None,
            session_dir=args.session_dir,
            node_name=args.node_name,
        )
        host, port = await nodelet.start(args.host, args.port)
        print(f"NODELET_PORT {port}", flush=True)
        print(f"NODELET_ID {nodelet.node_id.hex()}", flush=True)
        # Graceful SIGTERM/SIGINT: run Nodelet.stop() so spawned workers are
        # killed rather than orphaned (Node.stop() SIGTERMs this process; a
        # bare default handler would leak every worker).
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await nodelet.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
