"""Serving-at-scale bench: SSE load harness + prefix/chunking A/Bs.

Three rows for bench.py's ``serve_load`` section (gate
``RAY_TPU_BENCH_SERVE=0``):

* ``prefix_ab`` — in-process EngineCore A/B on a shared-system-prompt,
  multi-turn mix (16 requests): prefilled-token reduction from the radix
  prefix cache, with bit-identical outputs asserted against the cache-off
  arm.
* ``chunked_prefill_ab`` — one 4k-token prompt admitted while 8 streams
  decode, chunked vs unchunked on the same interleaved schedule: max
  observed ITL across the live streams, per arm.
* ``sse_load`` — hundreds of concurrent SSE streams (default 256; env
  ``RAY_TPU_BENCH_SERVE_STREAMS``) against a 2-replica `llm_deployment`
  through the real HTTP proxy: TTFT/ITL percentiles, goodput (completed
  tokens/s), shed count, half-stream count (must be 0), prefix-hit rate.

The SSE part owns a serve app inside the caller's runtime; bench.py runs
this module in a subprocess with its own ``ray_tpu.init``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List

# ----------------------------------------------------------------- utils


def _pct(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def _latency_row(values: List[float]) -> Dict[str, float]:
    return {
        "p50_ms": round(_pct(values, 0.50) * 1e3, 3),
        "p95_ms": round(_pct(values, 0.95) * 1e3, 3),
        "p99_ms": round(_pct(values, 0.99) * 1e3, 3),
    }


# ----------------------------------------------------- prefix caching A/B


def _prefix_workload():
    """Shared-system-prompt, multi-turn mix: 8 conversations whose first
    turn is a 32-token system prompt (50% of the prompt) + a 32-token
    unique user turn; each conversation then issues a follow-up that
    resends the whole first exchange plus 16 new tokens — the radix-cache
    sweet spot (16 requests total)."""
    system = [7 + (i % 40) for i in range(32)]
    turns = []
    for c in range(8):
        user = [60 + c * 3 + (i % 50) for i in range(32)]
        turns.append(system + user)
    return turns


def _run_prefix_arm(enable: bool) -> Dict[str, object]:
    from ray_tpu.llm import EngineCore

    # sequential generate() staggers admissions naturally: each request
    # completes (and populates the trie) before the next one admits
    core = EngineCore(seed=0, num_pages=512, page_size=8,
                      max_batch_tokens=128,
                      engine_name="bench-prefix",
                      enable_prefix_cache=enable)
    first = [core.generate(p, {"max_tokens": 8}) for p in _prefix_workload()]
    second = []
    for conv, res in zip(_prefix_workload(), first):
        followup = conv + res["tokens"] + [200 + (i % 30) for i in range(16)]
        second.append(core.generate(followup, {"max_tokens": 8}))
    core.cache.check_leaks()
    return {
        "outputs": [r["tokens"] for r in first + second],
        "prefilled_tokens": core.scheduler.prefilled_tokens,
        "prefix_hit_tokens": core.scheduler.prefix_hit_tokens,
    }


def run_prefix_ab() -> Dict[str, object]:
    off = _run_prefix_arm(False)
    on = _run_prefix_arm(True)
    assert on["outputs"] == off["outputs"], \
        "prefix cache changed sampled outputs"
    ratio = off["prefilled_tokens"] / max(on["prefilled_tokens"], 1)
    return {
        "requests": 16,
        "prefilled_tokens_off": off["prefilled_tokens"],
        "prefilled_tokens_on": on["prefilled_tokens"],
        "prefill_reduction_x": round(ratio, 2),
        "prefix_hit_tokens": on["prefix_hit_tokens"],
        "outputs_identical": True,
    }


# ---------------------------------------------------- chunked prefill A/B


def _run_chunked_arm(chunk: int, long_len: int) -> Dict[str, float]:
    from ray_tpu.llm import EngineCore
    from ray_tpu.models.gpt2 import GPT2Config

    cfg = GPT2Config(vocab_size=512, n_positions=long_len + 256,
                     n_embd=64, n_layer=2, n_head=4)
    core = EngineCore(cfg, seed=0, num_pages=(long_len + 512) // 16 + 64,
                      page_size=16,
                      max_batch_tokens=max(long_len + 64, 64),
                      engine_name="bench-chunk",
                      prefill_chunk_tokens=chunk)
    rids = [core.submit([3 + i] * 8, {"max_tokens": 48})
            for i in range(8)]
    # let the 8 streams reach steady-state decode, then drop the long
    # prompt into the running batch
    for _ in range(6):
        core.step()
    long_rid = core.submit([5 + (i % 400) for i in range(long_len)],
                           {"max_tokens": 4})
    core.run_until_done(rids + [long_rid])
    itls = [core.result(r)["max_itl"] for r in rids]
    return {"max_itl_s": max(itls)}


def run_chunked_ab(long_len: int = 4096) -> Dict[str, object]:
    unchunked = _run_chunked_arm(0, long_len)
    chunked = _run_chunked_arm(256, long_len)
    return {
        "long_prompt_tokens": long_len,
        "decode_streams": 8,
        "prefill_chunk_tokens": 256,
        "max_itl_unchunked_ms": round(unchunked["max_itl_s"] * 1e3, 2),
        "max_itl_chunked_ms": round(chunked["max_itl_s"] * 1e3, 2),
        "itl_ratio": round(chunked["max_itl_s"]
                           / max(unchunked["max_itl_s"], 1e-9), 3),
    }


# ------------------------------------------------------- SSE load harness


async def _drive_stream(session, url: str, prompt: List[int], tenant: str,
                        max_tokens: int, rec: Dict[str, object]) -> None:
    t0 = time.perf_counter()
    last = None
    try:
        async with session.post(
                url, json={"prompt_ids": prompt, "max_tokens": max_tokens,
                           "stream": True, "tenant": tenant},
                headers={"Accept": "text/event-stream"}) as resp:
            if resp.status == 429:
                rec["shed"] = True
                await resp.read()
                return
            async for raw in resp.content:
                line = raw.strip()
                if not line.startswith(b"data:"):
                    if line.startswith(b"event: error"):
                        rec["error"] = True
                    continue
                payload = line[len(b"data:"):].strip()
                if payload == b"[DONE]":
                    rec["done"] = True
                    return
                event = json.loads(payload)
                if event.get("done"):
                    continue
                now = time.perf_counter()
                if last is None:
                    rec["ttft"] = now - t0
                else:
                    rec["itls"].append(now - last)
                last = now
                rec["tokens"] += 1
    except Exception as e:
        rec["error"] = True
        rec["exc"] = repr(e)


async def _drive_load(port: int, num_streams: int,
                      max_tokens: int) -> List[Dict[str, object]]:
    import aiohttp

    url = f"http://127.0.0.1:{port}/llm"
    system = [7 + (i % 40) for i in range(32)]
    records: List[Dict[str, object]] = []
    conn = aiohttp.TCPConnector(limit=num_streams + 16)
    timeout = aiohttp.ClientTimeout(total=240)
    async with aiohttp.ClientSession(connector=conn,
                                     timeout=timeout) as session:
        tasks = []
        for i in range(num_streams):
            # shared-system-prompt mix: half the streams extend the common
            # system prompt, half are fully unique; two tenants
            if i % 2 == 0:
                prompt = system + [60 + (i % 100)] * 8
            else:
                prompt = [(11 + 5 * i + j) % 500 + 1 for j in range(24)]
            rec = {"shed": False, "done": False, "error": False,
                   "tokens": 0, "ttft": None, "itls": []}
            records.append(rec)
            tasks.append(_drive_stream(session, url, prompt,
                                       f"tenant-{i % 2}", max_tokens, rec))
        await asyncio.gather(*tasks)
    return records


def run_sse_load(num_streams: int = 256, num_replicas: int = 2,
                 max_tokens: int = 8,
                 metrics_wait_s: float = 30.0) -> Dict[str, object]:
    from ray_tpu import serve
    from ray_tpu.llm import llm_deployment
    from ray_tpu.util import state

    engine_kwargs = dict(num_pages=256, page_size=8, max_batch_tokens=256,
                         max_running=32, seed=0,
                         engine_name="bench-serve",
                         enable_prefix_cache=True,
                         prefill_chunk_tokens=64)
    app = llm_deployment(engine_kwargs=engine_kwargs,
                         num_replicas=num_replicas,
                         max_ongoing_requests=max(num_streams, 64),
                         admission_kwargs=dict(max_inflight=64,
                                               max_queue=num_streams,
                                               queue_deadline_s=120.0))
    serve.run(app, name="llm-load", route_prefix="/llm")
    port = serve.start(http_port=0)
    try:
        t0 = time.perf_counter()
        records = asyncio.new_event_loop().run_until_complete(
            _drive_load(port, num_streams, max_tokens))
        wall = time.perf_counter() - t0

        completed = [r for r in records if r["done"]]
        shed = [r for r in records if r["shed"]
                or (r["error"] and r["tokens"] == 0)]
        half = [r for r in records
                if r["tokens"] > 0 and not r["done"]]
        ttfts = [r["ttft"] for r in completed if r["ttft"] is not None]
        itls = [g for r in completed for g in r["itls"]]
        tokens = sum(r["tokens"] for r in completed)

        # per-engine metric fold (both replicas push under one engine
        # label); the push is periodic, so poll briefly for it to land
        view: Dict[str, float] = {}
        deadline = time.monotonic() + metrics_wait_s
        while time.monotonic() < deadline:
            view = state.summarize_llm().get("bench-serve", {})
            if view.get("requests", 0) >= len(completed):
                break
            time.sleep(1.0)
        return {
            "streams": num_streams,
            "replicas": num_replicas,
            "completed": len(completed),
            "shed": len(shed),
            "half_streams": len(half),
            "wall_s": round(wall, 2),
            "goodput_tokens_per_s": round(tokens / max(wall, 1e-9), 1),
            "ttft": _latency_row(ttfts),
            "itl": _latency_row(itls),
            "prefix_hit_rate": round(view.get("prefix_hit_rate", 0.0), 3),
            "prefix_hit_tokens": view.get("prefix_hit_tokens", 0.0),
            "sheds_by_engine_metric": view.get("shed", 0.0),
        }
    finally:
        serve.delete("llm-load")


# --------------------------------------------------------------- section


def run_serve_load_bench() -> Dict[str, object]:
    from ray_tpu._private.config import RayConfig

    streams = RayConfig.bench_serve_streams
    out: Dict[str, object] = {}
    out["prefix_ab"] = run_prefix_ab()
    out["chunked_prefill_ab"] = run_chunked_ab()
    out["sse_load"] = run_sse_load(num_streams=streams)
    return out
