"""RL sampling-loop bench: relaunch-IMPALA vs streaming-IMPALA env-steps/s.

The podracer streaming loop (rllib/podracer/stream.py) exists to delete
the per-fragment driver relaunch; this bench pins the claim with an
interleaved A/B on the same tiny CartPole policy.  Arms alternate within
each round (relaunch, streaming, relaunch, ...) so drift on a shared box
hits both equally, and the reported ratio uses each arm's best round
(min-of-3 wall clock == max-of-3 rate).  A third Sebulba arm (streaming +
InferencePool) runs once, not for rate supremacy — pooled inference on a
1-core CPU box pays an actor round-trip per rollout step — but to record
the batching occupancy and fragment-staleness percentiles that are the
point of the decoupled tier.

Keep the shape small: 2 runners x 4 envs x T=16 fragments means each
train() call moves O(100) env steps and the per-fragment loop shape —
exactly what relaunch vs streaming differ in — dominates the shared
rollout compute, so three interleaved rounds finish in well under a
minute per arm on CPU.
"""

from __future__ import annotations

import time
from typing import Any, Dict

ROUNDS = 3
WARMUP_ITERS = 3
MEASURE_ITERS = 20


def _build(mode: str, seed: int = 0):
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=16)
              .podracer(async_stream=(mode != "relaunch"),
                        inference_mode="pool" if mode == "sebulba"
                        else "local")
              .debugging(seed=seed))
    return config.build()


def _measure_arm(mode: str, seed: int = 0) -> Dict[str, Any]:
    """One round of one arm: fresh actors, jit warmup outside the clock,
    then MEASURE_ITERS train() calls."""
    algo = _build(mode, seed=seed)
    try:
        for _ in range(WARMUP_ITERS):
            r = algo.train()
        steps0 = r["num_env_steps_sampled_lifetime"]
        t0 = time.monotonic()
        for _ in range(MEASURE_ITERS):
            r = algo.train()
        dt = time.monotonic() - t0
        steps = r["num_env_steps_sampled_lifetime"] - steps0
        out = {
            "env_steps": int(steps),
            "seconds": round(dt, 4),
            "env_steps_per_s": round(steps / max(dt, 1e-9), 1),
            "job": algo._job,
        }
        if mode == "sebulba":
            import ray_tpu

            stats = ray_tpu.get(algo._pool.get_stats.remote(), timeout=60)
            out["inference_requests"] = int(stats["requests"])
            out["inference_max_batch_occupancy"] = \
                int(stats["max_batch_occupancy"])
            # staleness histogram is observed driver-side per fragment;
            # fold it the same way `ray_tpu summary rllib` does
            from ray_tpu.util import state

            row = state.summarize_rllib().get(algo._job, {})
            out["fragment_staleness_p50"] = row.get("staleness_p50")
            out["fragment_staleness_p95"] = row.get("staleness_p95")
        return out
    finally:
        algo.stop()


def run_rl_bench() -> Dict[str, Any]:
    """Interleaved best-of-ROUNDS A/B (+ one Sebulba occupancy row)."""
    rounds = {"relaunch": [], "streaming": []}
    for i in range(ROUNDS):
        # alternate arm order per round so slow drift on a shared box
        # penalizes both arms equally
        order = ("relaunch", "streaming") if i % 2 == 0 \
            else ("streaming", "relaunch")
        for mode in order:
            rounds[mode].append(_measure_arm(mode, seed=i))
    # per-arm MINIMUM across rounds: the conservative "this arm reliably
    # sustains at least X" estimator — one lucky OS-scheduling round must
    # not decide the A/B on a shared box
    floor = {mode: min(rs, key=lambda r: r["env_steps_per_s"])
             for mode, rs in rounds.items()}
    sebulba = _measure_arm("sebulba")
    return {
        "rounds": ROUNDS,
        "measure_iters": MEASURE_ITERS,
        "relaunch": floor["relaunch"],
        "streaming": floor["streaming"],
        "streaming_speedup": round(
            floor["streaming"]["env_steps_per_s"]
            / max(floor["relaunch"]["env_steps_per_s"], 1e-9), 3),
        "sebulba": sebulba,
        "all_rounds": {m: [r["env_steps_per_s"] for r in rs]
                       for m, rs in rounds.items()},
    }
