"""Always-on continuous sampling profiler (collapsed-stack, cluster-wide).

A per-process daemon thread samples ``sys._current_frames()`` at
``profile_hz`` (typed env-first flag; default off, 19 Hz is the canonical
enabled rate — prime, so it can't alias against 10/100 Hz periodic work)
and folds each thread's frames into collapsed-stack counts tagged
``{task_name, subsystem}``.  The fold dict is swapped out by
:func:`take_delta` and shipped piggyback on the existing worker->nodelet
metrics push; the nodelet forwards to the GCS which aggregates
cluster-wide, bounded by ``profile_max_stacks``.  ``ray_tpu flamegraph``
and the dashboard emit the aggregate in standard collapsed format
(``frame;frame;frame count`` — flamegraph.pl / speedscope compatible) or
as a self-contained SVG.

Disabled-cost contract: when ``profile_hz`` is 0 (the default) nothing is
started and the only hot-path cost anywhere is a module-attribute read of
:data:`SAMPLING` at metrics-push time — the same pattern as
``flight_recorder.RECORDING``.

Hang integration: the watchdog's one-shot formatted stacks (and any
``ray_tpu stack`` dump) fold through :func:`fold_formatted_stack` into the
same collapsed universe with a ``hung`` root tag, so a hung task shows up
in the flamegraph instead of only in /api/hangs.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# Module-level guard: False until a sampler thread is actually running.
# Hot paths (metrics push) read this one attribute and skip everything else
# when profiling is off — the zero-cost-when-disabled contract.
SAMPLING = False

_MAX_DEPTH = 64

_lock = threading.Lock()
# (task_name, subsystem, collapsed_stack) -> sample count, since last delta
_counts: Dict[Tuple[str, str, str], int] = {}
_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_samples_total = None  # lazily-registered Counter (sampler thread only)


def resolve_hz() -> float:
    """Env-first: a live ``RAY_TPU_PROFILE_HZ`` beats the cached flag so
    bench subprocesses (and operators flipping profiling on a running
    job's children) control it without re-initing config."""
    raw = os.environ.get("RAY_TPU_PROFILE_HZ")
    if raw is not None:
        try:
            return float(raw)
        except ValueError:
            return 0.0
    from ray_tpu._private.config import RayConfig

    return float(RayConfig.profile_hz)


def _frame_subsystem(frames: List[Any]) -> str:
    """Leaf-most ray_tpu module decides the subsystem tag: ``llm``,
    ``train``, ``serve``, ... with ``_private`` collapsed to ``core``;
    stacks that never enter ray_tpu are ``user`` code.  (_sample_once
    additionally re-tags task threads whose leaf frame is outside ray_tpu
    as ``user`` — the invoke machinery below a task body must not claim
    its samples.)"""
    for frame in frames:  # frames are leaf-first here
        mod = frame.f_globals.get("__name__") or ""
        if mod == "ray_tpu" or mod.startswith("ray_tpu."):
            parts = mod.split(".")
            sub = parts[1] if len(parts) > 1 else "core"
            return "core" if sub == "_private" else sub
    return "user"


def _fold_frames(leaf_frame: Any) -> Tuple[str, str]:
    """(collapsed_stack, subsystem) for one thread's current leaf frame.
    Collapsed stacks are root-first ';'-joined ``module:function`` frames
    with whitespace/semicolons scrubbed (collapsed format delimiters)."""
    frames = []
    f = leaf_frame
    depth = 0
    while f is not None and depth < _MAX_DEPTH:
        frames.append(f)
        f = f.f_back
        depth += 1
    subsystem = _frame_subsystem(frames)
    names = []
    for fr in reversed(frames):  # root-first
        mod = fr.f_globals.get("__name__") or "?"
        names.append(_scrub(f"{mod}:{fr.f_code.co_name}"))
    return ";".join(names), subsystem


def _scrub(frame: str) -> str:
    # collapsed format reserves ';' (frame sep) and ' ' (count sep)
    return frame.replace(";", ",").replace(" ", "_")


def _sample_once(get_tags: Callable[[int], Optional[str]]) -> int:
    """One sampling tick: fold every thread except the sampler itself.
    Returns the number of threads sampled."""
    me = threading.get_ident()
    sampled = 0
    # sys._current_frames() is a consistent point-in-time snapshot taken
    # under the GIL; no target-thread cooperation needed
    for ident, frame in sys._current_frames().items():
        if ident == me:
            continue
        try:
            stack, subsystem = _fold_frames(frame)
        except Exception:
            continue  # frame raced with thread exit
        task = get_tags(ident) or ""
        if task and subsystem == "core":
            # a task thread whose leaf frame is outside ray_tpu is running
            # user code — the core_worker invoke machinery below it must
            # not claim the sample (library subsystems like llm/train win
            # before this: they are leaf-most of the invoke frames)
            leaf_mod = frame.f_globals.get("__name__") or ""
            if not (leaf_mod == "ray_tpu" or leaf_mod.startswith("ray_tpu.")):
                subsystem = "user"
        key = (task, subsystem, stack)
        with _lock:
            _counts[key] = _counts.get(key, 0) + 1
        sampled += 1
    return sampled


def _loop(hz: float, get_tags: Callable[[int], Optional[str]]) -> None:
    global _samples_total
    from ray_tpu._private.metrics import Counter

    if _samples_total is None:
        _samples_total = Counter(
            "profile_samples_total",
            "Profiler samples folded in this process (one per thread per "
            "tick while profile_hz > 0)")
    period = 1.0 / hz
    while not _stop.wait(period):
        try:
            n = _sample_once(get_tags)
            if n:
                _samples_total.inc(n)
        except Exception:
            pass  # a failed tick must never kill the sampler


def ensure_started(
        get_tags: Optional[Callable[[int], Optional[str]]] = None) -> bool:
    """Start this process's sampler thread if ``profile_hz`` > 0 and it is
    not already running.  ``get_tags(thread_ident)`` maps a sampled thread
    to the task name it is executing (pull-based from the core worker's
    running-task registry — the task hot path is never instrumented).
    Returns True when sampling is (now) active."""
    global _thread, SAMPLING
    hz = resolve_hz()
    if hz <= 0:
        return False
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop.clear()
        _thread = threading.Thread(
            target=_loop, args=(hz, get_tags or (lambda ident: None)),
            name="ray_tpu-profiler", daemon=True)
        _thread.start()
        SAMPLING = True
    return True


def stop() -> None:
    """Stop the sampler (tests); pending counts stay until take_delta."""
    global _thread, SAMPLING
    _stop.set()
    with _lock:
        t, _thread = _thread, None
        SAMPLING = False
    if t is not None:
        t.join(timeout=2)


def take_delta() -> List[List[Any]]:
    """Swap out and return the counts accumulated since the last call, as
    ``[[task_name, subsystem, stack, count], ...]`` (JSON-ready — this is
    the wire shape piggybacked on the metrics push)."""
    global _counts
    with _lock:
        counts, _counts = _counts, {}
    return [[task, subsystem, stack, n]
            for (task, subsystem, stack), n in counts.items()]


def peek() -> List[List[Any]]:
    """Non-destructive view of the pending local counts (read surfaces use
    this so they never steal samples from the push path)."""
    with _lock:
        counts = dict(_counts)
    return [[task, subsystem, stack, n]
            for (task, subsystem, stack), n in counts.items()]


# ------------------------------------------------ formatted-stack folding

_FRAME_RE = re.compile(r'File "([^"]+)", line \d+, in (\S+)')


def fold_formatted_stack(text: str) -> str:
    """Fold a ``traceback.format_stack`` text blob (hang-watchdog one-shot
    stacks, ``ray_tpu stack`` dumps) into one root-first collapsed stack so
    point-in-time dumps land in the same flamegraph universe as sampled
    profiles.  Frame names are ``filename:function`` (no module objects to
    consult in text form)."""
    names = []
    for path, func in _FRAME_RE.findall(text):
        base = os.path.basename(path)
        if base.endswith(".py"):
            base = base[:-3]
        names.append(_scrub(f"{base}:{func}"))
    return ";".join(names)  # format_stack is already root-first


# ---------------------------------------------------- rendering / output

def collapsed_lines(entries: List[List[Any]],
                    tag_hung: bool = False,
                    critical_tasks: Optional[set] = None) -> List[str]:
    """Render aggregate entries (``[task, subsystem, stack, count]``, with
    an optional trailing tag element) as collapsed-stack lines::

        subsystem;task:NAME;frame;frame;frame COUNT

    Root tag frames: ``hung`` (one-shot watchdog stacks, when tag_hung) and
    ``on_critical_path`` (tasks in ``critical_tasks`` — a read-time join
    against a computed critical path).  Frames never contain spaces, so the
    output round-trips through any flamegraph.pl-style parser."""
    merged: Dict[str, int] = {}
    for entry in entries:
        task, subsystem, stack, count = entry[:4]
        tag = entry[4] if len(entry) > 4 else None
        roots = []
        if tag == "hung" and tag_hung:
            roots.append("hung")
        if critical_tasks and task in critical_tasks:
            roots.append("on_critical_path")
        roots.append(_scrub(subsystem or "user"))
        if task:
            roots.append(_scrub(f"task:{task}"))
        line = ";".join(roots + ([stack] if stack else []))
        merged[line] = merged.get(line, 0) + int(count)
    return [f"{stack} {count}" for stack, count in
            sorted(merged.items())]


def parse_collapsed(lines: List[str]) -> Dict[Tuple[str, ...], int]:
    """flamegraph.pl-style parser: ``frame;frame;frame count`` per line,
    count after the last space.  Used by tests to assert our emitted format
    round-trips, and by render_svg."""
    out: Dict[Tuple[str, ...], int] = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack or not count.isdigit():
            raise ValueError(f"not collapsed-stack format: {line!r}")
        key = tuple(stack.split(";"))
        out[key] = out.get(key, 0) + int(count)
    return out


def render_svg(lines: List[str], title: str = "ray_tpu flamegraph") -> str:
    """Self-contained SVG flamegraph from collapsed lines: a frame trie
    with width proportional to inclusive sample count, hover titles with
    counts/percentages.  No JS dependencies — any browser renders it."""
    stacks = parse_collapsed(lines)
    total = sum(stacks.values()) or 1

    # trie: name -> [inclusive_count, children_dict]
    root: Dict[str, list] = {}
    for frames, count in sorted(stacks.items()):
        level = root
        for name in frames:
            node = level.setdefault(name, [0, {}])
            node[0] += count
            level = node[1]

    width, row_h, font = 1200.0, 16, 11
    rects: List[str] = []
    max_depth = [0]

    def emit(level: Dict[str, list], x: float, depth: int,
             scale: float) -> None:
        max_depth[0] = max(max_depth[0], depth)
        for name in sorted(level):
            count, children = level[name]
            w = count * scale
            if w < 0.5:
                x += w
                continue
            y = depth * row_h
            hue = 10 + (hash(name) % 40)  # stable warm palette
            label = name if w > font * 0.6 * len(name) else (
                name[: max(int(w / (font * 0.6)), 0)] or "")
            pct = 100.0 * count / total
            rects.append(
                f'<g><title>{_esc(name)} ({count} samples, {pct:.2f}%)'
                f'</title>'
                f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                f'height="{row_h - 1}" fill="hsl({hue},75%,62%)" '
                f'rx="1"/>'
                f'<text x="{x + 2:.1f}" y="{y + row_h - 4}" '
                f'font-size="{font}" font-family="monospace">'
                f'{_esc(label)}</text></g>')
            emit(children, x, depth + 1, scale)
            x += w

    emit(root, 0.0, 1, width / total)
    height = (max_depth[0] + 2) * row_h
    header = (f'<text x="4" y="{row_h - 4}" font-size="{font + 1}" '
              f'font-family="monospace" font-weight="bold">'
              f'{_esc(title)} — {total} samples</text>')
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{int(width)}" '
            f'height="{height}" viewBox="0 0 {int(width)} {height}">'
            f'<rect width="100%" height="100%" fill="#fdfdf6"/>'
            f'{header}{"".join(rects)}</svg>')


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;"))
