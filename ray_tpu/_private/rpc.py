"""Asyncio RPC: bidirectional, multiplexed, zero-copy-friendly message transport.

Counterpart of the reference's gRPC wrapper layer (reference: src/ray/rpc/grpc_server.h,
client_call.h, server_call.h).  Design differences, deliberately TPU/host-native:

- One TCP (or unix-domain) connection per process pair, *bidirectional*: either side
  can issue requests, so pub/sub pushes and actor-task pushes ride the same socket
  instead of long-polling (reference pubsub uses long-poll, pubsub.proto:232).
- Frames carry pickle-5 out-of-band buffers natively: a numpy payload is written
  straight from its memoryview with no intermediate concatenation, and received as a
  view over the read buffer.  This is the host-DRAM data plane that feeds TPU
  infeed; the device-to-device plane is the collective layer, not RPC.
- Handlers are asyncio coroutines registered by method name; per-handler stats are
  recorded when RayConfig.event_stats is on (reference: common/event_stats.h).

Frame layout: [4B header_len][msgpack header][8B inband_len][inband pickle]
              [8B buf_len][buf bytes] * header["nbufs"]
Header: {"t": 0 req | 1 res | 2 err | 3 notify | 4 hello, "id": int,
"m": method} — hello frames carry version negotiation (see the protocol
contract block below).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import sys
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import fault_injection
from ray_tpu._private.config import RayConfig

logger = logging.getLogger(__name__)

T_REQ, T_RES, T_ERR, T_NOTIFY, T_HELLO = 0, 1, 2, 3, 4

# ------------------------------------------------------- protocol contract
# Wire format (the IDL-lite; reference analogue: the protobuf service
# definitions in src/ray/protobuf — here the schema is this documented
# msgpack frame plus pickled payloads, deliberately codegen-free):
#
#   u32 header_len | msgpack header | u64 inband_len | pickled payload
#   | per-OOB-buffer: u64 len | raw bytes
#
# header: {"t": T_*, "id": int, "m": method, "nbufs": int}
#   T_REQ    request; "m" names an rpc_<m> handler on the peer
#   T_RES    response (same id); payload is the handler's return value
#   T_ERR    response (same id); payload is the raised exception
#   T_NOTIFY fire-and-forget request (id 0, no response)
#   T_HELLO  version/feature negotiation, sent once by the dialing side
#            immediately after connect: {"t": T_HELLO, "v": int,
#            "min": int, "features": [str], "name": str}.  The server
#            answers with its own T_HELLO.  A peer whose "min" exceeds
#            PROTOCOL_VERSION is refused (T_ERR + close).  Peers that
#            never send T_HELLO (older builds) keep working:
#            peer_version stays None and no feature gating applies.
#
# The per-method schema this frame carries (every registered handler, the
# request keys it reads, the reply keys it returns, every static call
# site) is extracted from the tree by the wire-contract lint pass and
# checked in as docs/WIRE_CONTRACT.md + ray_tpu/_lint/wire_contract.json.
# Changing the wire surface without bumping PROTOCOL_VERSION below or
# regenerating the snapshot (`python -m ray_tpu lint --update-contract`)
# is a wire-contract.drift finding anchored on the next line.
PROTOCOL_VERSION = 1
MIN_COMPATIBLE_VERSION = 1
PROTOCOL_FEATURES = ("pickle5-oob", "batched-tasks", "chunked-pull",
                     "task-events", "dag-channels", "rpc-batch")

_OOB_THRESHOLD = 64 * 1024  # RPC-level threshold for out-of-band buffers

# Messages whose encoded payload exceeds this ride their own frame instead
# of the per-tick batch: coalescing exists to amortize syscalls over SMALL
# control messages (seals, releases, ref-count updates), and batching a big
# payload would just add one memcpy in front of the same socket write.
_BATCH_INBAND_MAX = 32 * 1024
# The per-tick batch frame's method name.  Items are (t, id, method, inband)
# tuples; the receiver dispatches them in order inside one task.
_BATCH_METHOD = "__batch__"

Handler = Callable[["Connection", Any], Awaitable[Any]]


class ConnectionLost(ConnectionError):
    pass


class RaySerializationError(RuntimeError):
    """A message payload could not be encoded/decoded; fails one call, not the link."""


def _encode(obj: Any) -> Tuple[bytes, list]:
    buffers: list = []

    def cb(pb: pickle.PickleBuffer) -> bool:
        mv = pb.raw()
        if mv.nbytes < _OOB_THRESHOLD:
            return True
        buffers.append(mv)
        return False

    try:
        inband = pickle.dumps(obj, protocol=5, buffer_callback=cb)
    except Exception:
        # Control-plane payloads are plain data; anything exotic (closures,
        # locally-defined exception classes) falls back to cloudpickle.
        import cloudpickle

        buffers.clear()
        inband = cloudpickle.dumps(obj, protocol=5, buffer_callback=cb)
    return inband, buffers


class Connection:
    """A bidirectional RPC peer over one stream."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handlers: Dict[str, Handler],
        on_close: Optional[Callable[["Connection"], None]] = None,
        name: str = "",
    ):
        self._reader = reader
        self._writer = writer
        self._handlers = handlers
        self._on_close = on_close
        self.name = name
        self._id_gen = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._dispatch_tasks: set = set()
        # Per-tick coalescing buffer: (t, id, method, inband) items flushed
        # as ONE __batch__ frame by a call_soon callback — N small control
        # messages cost one syscall instead of N (see notify_coalesced /
        # call_pipelined).
        self._obuf: list = []
        self._obuf_scheduled = False
        self._closed = False
        self._loop = asyncio.get_event_loop()
        self._send_lock = asyncio.Lock()
        self._recv_task = self._loop.create_task(self._recv_loop())
        self._handler_stats: Dict[str, list] = {}
        # Arbitrary metadata slot for the server side (e.g. registered worker id).
        self.context: Dict[str, Any] = {}
        # Version negotiation state (None until the peer's T_HELLO arrives;
        # stays None for pre-handshake peers, which remain fully supported).
        self.peer_version: Optional[int] = None
        self.peer_features: frozenset = frozenset()
        self.peer_name: str = ""

    @property
    def closed(self) -> bool:
        return self._closed

    def peername(self):
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None

    # Below this, a chunk is coalesced into one write; above it, handed to
    # the transport as-is (coalescing would memcpy a large payload just to
    # save a syscall).
    _COALESCE_MAX = 64 * 1024

    async def _send_frame(self, header: dict, inband: bytes, buffers: list):
        if fault_injection.ENABLED:
            act = fault_injection.hit("rpc.frame.send",
                                      detail=header.get("m") or "")
            if act == "drop":
                return
            if act == "delay":
                await asyncio.sleep(fault_injection.delay_s())
            elif act == "sever":
                self._writer.close()
                raise ConnectionLost("chaos: link severed")
            elif act == "dup":
                await self._send_frame_raw(header, inband, buffers)
        await self._send_frame_raw(header, inband, buffers)

    async def _send_frame_raw(self, header: dict, inband: bytes,
                              buffers: list):
        header_b = msgpack.packb(header)
        async with self._send_lock:
            # Coalesce the small chunks (length prefixes, header, small
            # inband) into ONE transport write: each StreamWriter.write is an
            # eager socket send, and per-frame syscall count dominates small-
            # RPC cost (measured ~0.15 ms/syscall on 1-vCPU virtio).
            w = self._writer
            pending = bytearray()

            def emit(chunk):
                if len(chunk) < self._COALESCE_MAX:
                    pending.extend(chunk)
                else:
                    if pending:
                        w.write(bytes(pending))
                        pending.clear()
                    w.write(chunk)

            emit(len(header_b).to_bytes(4, "little"))
            emit(header_b)
            emit(len(inband).to_bytes(8, "little"))
            emit(inband)
            for b in buffers:
                emit(b.nbytes.to_bytes(8, "little"))
                emit(b)
            if pending:
                w.write(bytes(pending))
            await w.drain()

    async def call(self, method: str, obj: Any = None, timeout: Optional[float] = None) -> Any:
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        inband, buffers = _encode(obj)  # encode before registering: may raise
        req_id = next(self._id_gen)
        fut = self._loop.create_future()
        self._pending[req_id] = fut
        try:
            await self._send_frame({"t": T_REQ, "id": req_id, "m": method, "nbufs": len(buffers)}, inband, buffers)
        except (ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise ConnectionLost(str(e)) from e
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(req_id, None)

    def call_sync(self, method: str, obj: Any = None, timeout: Optional[float] = None) -> Any:
        """Thread-safe blocking call from outside the event loop."""
        fut = asyncio.run_coroutine_threadsafe(self.call(method, obj, timeout), self._loop)
        return fut.result()

    async def notify(self, method: str, obj: Any = None):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        inband, buffers = _encode(obj)
        try:
            await self._send_frame({"t": T_NOTIFY, "id": 0, "m": method, "nbufs": len(buffers)}, inband, buffers)
        except (ConnectionError, OSError) as e:
            raise ConnectionLost(str(e)) from e

    def notify_sync(self, method: str, obj: Any = None, timeout: Optional[float] = 30.0):
        fut = asyncio.run_coroutine_threadsafe(self.notify(method, obj), self._loop)
        return fut.result(timeout)

    # ------------------------------------------------ coalesced control plane
    # Small control frames (seal/release/ref-count/metric/event pushes) were
    # one frame + one syscall + often one round trip EACH; on a shared-core
    # host the per-frame cost dominates the control plane.  The batch layer
    # buffers items for one loop tick and ships them as a single __batch__
    # frame; the receiver dispatches them in order inside one task.

    def notify_coalesced(self, method: str, obj: Any = None) -> None:
        """Fire-and-forget notify riding the per-tick batch frame.  MUST be
        called from the IO-loop thread (use notify_coalesced_threadsafe
        elsewhere).  Large/out-of-band payloads fall back to a plain notify
        frame."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        inband, buffers = _encode(obj)
        if buffers or len(inband) > _BATCH_INBAND_MAX:
            self._spawn_task(self._notify_quietly(method, inband, buffers))
            return
        self._queue_batch_item(T_NOTIFY, 0, method, inband)

    def notify_coalesced_threadsafe(self, method: str, obj: Any = None) -> None:
        """notify_coalesced from any thread: the payload is encoded on the
        caller's thread (keeping pickling off the IO loop) and the queue
        append hops to the loop."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        inband, buffers = _encode(obj)
        try:
            if buffers or len(inband) > _BATCH_INBAND_MAX:
                self._loop.call_soon_threadsafe(
                    self._spawn_task,
                    self._notify_quietly(method, inband, buffers))
            else:
                self._loop.call_soon_threadsafe(
                    self._queue_batch_item, T_NOTIFY, 0, method, inband)
        except RuntimeError:
            pass  # loop closed: shutdown path, drop like a lost notify

    async def _notify_quietly(self, method: str, inband: bytes, buffers: list):
        try:
            await self._send_frame(
                {"t": T_NOTIFY, "id": 0, "m": method, "nbufs": len(buffers)},
                inband, buffers)
        except (ConnectionError, OSError):
            pass  # fire-and-forget semantics match notify-on-dead-peer

    async def call_pipelined(self, method: str, obj: Any = None,
                             timeout: Optional[float] = None) -> Any:
        """Like call(), but the request frame rides the per-tick batch, so N
        same-tick requests cost one write.  For small, fast handlers only —
        batched requests are dispatched sequentially on the receiver."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        inband, buffers = _encode(obj)
        if buffers or len(inband) > _BATCH_INBAND_MAX:
            return await self.call(method, obj, timeout)
        req_id = next(self._id_gen)
        fut = self._loop.create_future()
        self._pending[req_id] = fut
        self._queue_batch_item(T_REQ, req_id, method, inband)
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(req_id, None)

    def _queue_batch_item(self, t: int, rid: int, method: str,
                          inband: bytes) -> None:
        if self._closed:
            return  # pending REQ futures are failed by _shutdown
        self._obuf.append((t, rid, method, inband))
        if not self._obuf_scheduled:
            self._obuf_scheduled = True
            self._loop.call_soon(self._flush_obuf)

    def _flush_obuf(self) -> None:
        self._obuf_scheduled = False
        if not self._obuf:
            return
        items, self._obuf = self._obuf, []
        if self._closed:
            return
        self._spawn_task(self._send_batch(items))

    async def _send_batch(self, items: list) -> None:
        inband, buffers = _encode(items)
        try:
            await self._send_frame(
                {"t": T_NOTIFY, "id": 0, "m": _BATCH_METHOD,
                 "nbufs": len(buffers)}, inband, buffers)
        except (ConnectionError, OSError) as e:
            # REQ items' futures are registered in _pending: fail them like
            # a lost connection would (the recv loop may not notice yet).
            for t, rid, _m, _b in items:
                if t == T_REQ:
                    fut = self._pending.pop(rid, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(ConnectionLost(str(e)))

    async def _dispatch_batch(self, items: list) -> None:
        """Receiver side of the batch frame: items run in order, one task."""
        for item in items:
            try:
                t, rid, method, inband = item
                obj = pickle.loads(inband)
            except Exception as decode_err:
                self._handle_decode_error(
                    {"id": item[1] if len(item) > 1 else 0,
                     "m": item[2] if len(item) > 2 else "?"},
                    item[0] if item else T_NOTIFY, decode_err)
                continue
            if t == T_REQ:
                await self._dispatch({"t": t, "id": rid, "m": method}, obj)
            elif t == T_NOTIFY:
                await self._dispatch({"t": t, "id": 0, "m": method}, obj,
                                     needs_reply=False)
            elif t in (T_RES, T_ERR):
                fut = self._pending.pop(rid, None)
                if fut is not None and not fut.done():
                    if t == T_RES:
                        fut.set_result(obj)
                    elif isinstance(obj, BaseException):
                        fut.set_exception(obj)
                    else:
                        fut.set_exception(RaySerializationError(
                            f"malformed error reply: {obj!r}"))

    async def _read_exactly(self, n: int) -> bytes:
        return await self._reader.readexactly(n)

    async def _recv_loop(self):
        try:
            while True:
                hlen = int.from_bytes(await self._read_exactly(4), "little")
                header = msgpack.unpackb(await self._read_exactly(hlen))
                ilen = int.from_bytes(await self._read_exactly(8), "little")
                inband = await self._read_exactly(ilen)
                buffers = []
                for _ in range(header.get("nbufs", 0)):
                    blen = int.from_bytes(await self._read_exactly(8), "little")
                    buffers.append(await self._read_exactly(blen))
                t = header["t"]
                try:
                    obj = pickle.loads(inband, buffers=buffers)
                except Exception as decode_err:
                    # A bad payload fails only this message, not the connection.
                    self._handle_decode_error(header, t, decode_err)
                    continue
                if t == T_HELLO:
                    self._on_hello(header)
                    continue
                if t == T_NOTIFY and header.get("m") == _BATCH_METHOD:
                    # coalesced control frame: dispatch items in order
                    # inside ONE task (an asyncio task per item would
                    # recreate the overhead batching removes)
                    self._spawn_task(self._dispatch_batch(obj))
                elif t == T_REQ:
                    self._spawn_dispatch(header, obj)
                elif t == T_NOTIFY:
                    self._spawn_dispatch(header, obj, needs_reply=False)
                elif t == T_ERR and header.get("m") == "__hello__":
                    # handshake refusal: no pending future carries id 0 —
                    # surface the cause loudly before the peer closes on us
                    logger.error("peer refused connection %s at handshake: "
                                 "%s", self.name, obj)
                    for fut in list(self._pending.values()):
                        if not fut.done():
                            fut.set_exception(
                                obj if isinstance(obj, BaseException)
                                else ConnectionLost(str(obj)))
                    self._pending.clear()
                elif t in (T_RES, T_ERR):
                    fut = self._pending.pop(header["id"], None)
                    if fut is not None and not fut.done():
                        if t == T_RES:
                            fut.set_result(obj)
                        elif isinstance(obj, BaseException):
                            fut.set_exception(obj)
                        else:
                            fut.set_exception(
                                RaySerializationError(f"malformed error reply: {obj!r}")
                            )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            return
        except Exception:
            logger.exception("rpc recv loop error on %s", self.name)
        finally:
            await self._shutdown()

    def _on_hello(self, header: dict) -> None:
        """Record the peer's protocol version/features; answer a dialing
        peer's hello with ours (ack'd, so the exchange terminates)."""
        self.peer_version = header.get("v")
        self.peer_features = frozenset(header.get("features") or ())
        self.peer_name = header.get("name") or ""
        peer_min = header.get("min", header.get("v", 0))
        reason = None
        if peer_min is not None and peer_min > PROTOCOL_VERSION:
            reason = (f"peer needs protocol >= {peer_min}, this build "
                      f"speaks {PROTOCOL_VERSION}")
        elif (self.peer_version or 0) < MIN_COMPATIBLE_VERSION:
            reason = (f"peer speaks protocol {self.peer_version}, this "
                      f"build requires >= {MIN_COMPATIBLE_VERSION}")
        if reason is not None:
            logger.error("refusing connection %s: %s",
                         self.peer_name or self.name, reason)

            async def refuse():
                try:
                    inband, buffers = _encode(ConnectionLost(
                        f"incompatible protocol: {reason}"))
                    await self._send_frame(
                        {"t": T_ERR, "id": 0, "m": "__hello__",
                         "nbufs": len(buffers)}, inband, buffers)
                finally:
                    await self._shutdown()

            self._spawn_task(refuse())
            return
        if not header.get("ack"):
            async def _ack():
                try:
                    await self.send_hello(ack=True)
                except (ConnectionError, OSError):
                    pass  # peer vanished between hello and ack

            self._spawn_task(_ack())

    async def send_hello(self, ack: bool = False) -> None:
        """Raises ConnectionError/OSError if the link is already dead — the
        dialing side's connect() retry loop relies on that; the server-side
        ack path wraps it (a reply to a vanished peer is a no-op)."""
        inband, buffers = _encode(None)
        await self._send_frame(
            {"t": T_HELLO, "v": PROTOCOL_VERSION,
             "min": MIN_COMPATIBLE_VERSION,
             "features": list(PROTOCOL_FEATURES), "name": self.name,
             "ack": ack, "id": 0, "m": "__hello__",
             "nbufs": len(buffers)}, inband, buffers)

    def _handle_decode_error(self, header: dict, t: int, decode_err: Exception):
        names = ("REQ", "RES", "ERR", "NOTIFY", "HELLO")
        err = RaySerializationError(
            f"failed to decode {names[t] if t < len(names) else t} payload "
            f"for method {header.get('m')!r}: {decode_err!r}"
        )
        if t in (T_RES, T_ERR):
            fut = self._pending.pop(header["id"], None)
            if fut is not None and not fut.done():
                fut.set_exception(err)
        elif t == T_REQ:
            async def reply_err():
                try:
                    inband, buffers = _encode(err)
                    await self._send_frame(
                        {"t": T_ERR, "id": header["id"], "m": header.get("m"), "nbufs": len(buffers)},
                        inband,
                        buffers,
                    )
                except (ConnectionError, OSError):
                    pass
            self._spawn_task(reply_err())
        else:
            logger.warning("dropping undecodable notify: %s", err)

    def _spawn_dispatch(self, header: dict, obj: Any, needs_reply: bool = True):
        self._spawn_task(self._dispatch(header, obj, needs_reply=needs_reply))

    def _spawn_task(self, coro):
        # Keep a strong reference: asyncio only holds weak refs to tasks, so an
        # in-flight handler could otherwise be garbage-collected mid-run.
        task = self._loop.create_task(coro)
        self._dispatch_tasks.add(task)
        task.add_done_callback(self._dispatch_tasks.discard)

    async def _dispatch(self, header: dict, obj: Any, needs_reply: bool = True):
        method = header["m"]
        handler = self._handlers.get(method)
        start = time.monotonic() if RayConfig.event_stats else 0.0
        # Run the handler first; a ConnectionError raised *by the handler*
        # (e.g. it forwarded work to a dead peer) is an application error and
        # must still produce a T_ERR reply — only failures sending on *this*
        # connection are swallowed.
        result: Any = None
        error: Optional[BaseException] = None
        try:
            if handler is None:
                raise AttributeError(f"no rpc handler for method {method!r}")
            result = await handler(self, obj)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            error = e
        try:
            if needs_reply:
                if error is None:
                    inband, buffers = _encode(result)
                    if not buffers and len(inband) <= _BATCH_INBAND_MAX:
                        # small reply: ride the per-tick batch so a burst of
                        # same-tick completions answers in one frame
                        self._queue_batch_item(
                            T_RES, header["id"], method, inband)
                    else:
                        await self._send_frame({"t": T_RES, "id": header["id"], "m": method, "nbufs": len(buffers)}, inband, buffers)
                elif not self._closed:
                    try:
                        inband, buffers = _encode(error)
                    except Exception:
                        inband, buffers = _encode(RuntimeError(f"unpicklable handler error: {error!r}"))
                    await self._send_frame({"t": T_ERR, "id": header["id"], "m": method, "nbufs": len(buffers)}, inband, buffers)
            elif error is not None:
                logger.error("error in notify handler %s: %r", method, error)
        except (ConnectionError, OSError):
            pass
        finally:
            if RayConfig.event_stats:
                dt = time.monotonic() - start
                st = self._handler_stats.setdefault(method, [0, 0.0])
                st[0] += 1
                st[1] += dt

    def handler_stats(self) -> Dict[str, Tuple[int, float]]:
        return {k: (v[0], v[1]) for k, v in self._handler_stats.items()}

    async def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        self._obuf.clear()  # queued REQ items fail via the pending sweep
        for fut in list(self._pending.values()):
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        # Cancel in-flight inbound handlers: they act on behalf of a peer that
        # can no longer receive the reply.
        for task in list(self._dispatch_tasks):
            task.cancel()
        try:
            self._writer.close()
        except Exception:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self):
        self._recv_task.cancel()
        await self._shutdown()

    def close_threadsafe(self):
        asyncio.run_coroutine_threadsafe(self.close(), self._loop)


class Server:
    """RPC server: accepts connections, each becomes a bidirectional Connection."""

    def __init__(self, handlers: Dict[str, Handler], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()
        self.on_disconnect: Optional[Callable[[Connection], None]] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._accept, host=host, port=port)
        sock = self._server.sockets[0]
        addr = sock.getsockname()
        return addr[0], addr[1]

    async def _accept(self, reader, writer):
        conn = Connection(reader, writer, self.handlers, on_close=self._handle_close, name=f"{self.name}-peer")
        self.connections.add(conn)

    def _handle_close(self, conn: Connection):
        self.connections.discard(conn)
        if self.on_disconnect is not None:
            try:
                self.on_disconnect(conn)
            except Exception:
                logger.exception("on_disconnect failed")

    async def stop(self):
        # Close live connections before wait_closed(): since py3.12 wait_closed
        # blocks until every connection handed out by start_server is closed.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass


async def connect(
    host: str,
    port: int,
    handlers: Optional[Dict[str, Handler]] = None,
    name: str = "client",
    retry_timeout_s: float = 10.0,
) -> Connection:
    """Dial a server, retrying while it boots."""
    deadline = time.monotonic() + retry_timeout_s
    delay = 0.05
    while True:
        try:
            reader, writer = await asyncio.open_connection(host, port)
            conn = Connection(reader, writer, handlers or {}, name=name)
            # fire-and-forget version negotiation: the reply sets
            # conn.peer_version whenever the server speaks hello
            await conn.send_hello()
            return conn
        except (ConnectionError, OSError):
            if time.monotonic() >= deadline:
                raise
            await asyncio.sleep(delay)
            delay = min(delay * 2, 1.0)


class EventLoopThread:
    """A dedicated thread running an asyncio loop — the per-process 'io_service'.

    Counterpart of the reference's instrumented asio event loop
    (src/ray/common/asio/).  User/task code stays on the main thread; all RPC IO
    happens here.
    """

    def __init__(self, name: str = "ray-tpu-io",
                 stall_threshold_s: Optional[float] = None):
        self.loop = asyncio.new_event_loop()
        self.name = name
        self._beat = time.monotonic()
        self._stall_logged = 0.0
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()
        if stall_threshold_s is None:
            # env re-read per loop start (set_env retunes live processes);
            # the registered flag carries the typed default
            try:
                env = os.environ.get("RAY_TPU_LOOP_STALL_THRESHOLD_S")
                from ray_tpu._private.config import RayConfig

                stall_threshold_s = float(env) if env is not None \
                    else RayConfig.loop_stall_threshold_s
            except ValueError:
                stall_threshold_s = 5.0  # a bad knob must not kill startup
        if stall_threshold_s > 0:
            self._start_stall_detector(stall_threshold_s)

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    # ---------------------------------------------------- stall detection
    def _start_stall_detector(self, threshold_s: float) -> None:
        """Watchdog for the 'one slow handler starves every connection'
        class of bug (reference: the instrumented asio event loop's
        event_stats + stall warnings, src/ray/common/asio/).  A heartbeat
        callback stamps the loop's liveness; a daemon thread warns — with
        the loop thread's current stack — whenever the stamp goes stale."""
        import traceback

        def beat():
            self._beat = time.monotonic()
            if not self.loop.is_closed():
                self.loop.call_later(min(threshold_s / 4, 1.0), beat)

        try:
            self.loop.call_soon_threadsafe(beat)
        except RuntimeError:
            return

        def watch():
            while self._thread.is_alive() and not self.loop.is_closed():
                time.sleep(threshold_s / 2)
                stalled = time.monotonic() - self._beat
                if stalled > threshold_s and \
                        time.monotonic() - self._stall_logged > 30.0:
                    self._stall_logged = time.monotonic()
                    frame = sys._current_frames().get(self._thread.ident)
                    where = "".join(traceback.format_stack(frame)) \
                        if frame is not None else "<no frame>"
                    logger.warning(
                        "event loop %r stalled for %.1fs — a handler is "
                        "blocking the IO thread; current stack:\n%s",
                        self.name, stalled, where)

        threading.Thread(target=watch, name=f"{self.name}-stall-watch",
                         daemon=True).start()

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the loop, blocking the calling thread."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    def on_loop_thread(self) -> bool:
        """True when the caller IS the IO-loop thread.  Any blocking call
        (call_sync / run) from the loop thread deadlocks the loop — callers
        use this to downgrade to fire-and-forget."""
        return threading.current_thread() is self._thread

    def spawn(self, coro) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        def _cancel_all():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            # Stop on a later callback so cancelled tasks get a chance to run
            # their finally blocks before the loop halts.
            self.loop.call_soon(self.loop.stop)

        # call_soon_threadsafe works whether or not run_forever has started yet;
        # it fails only once the loop is closed.
        try:
            self.loop.call_soon_threadsafe(_cancel_all)
        except RuntimeError:
            pass  # loop already closed
        self._thread.join(timeout=5)
        if not self.loop.is_running() and not self.loop.is_closed():
            self.loop.close()
