"""Global worker singleton + init/shutdown/connect.

Counterpart of the reference's driver bootstrap (reference:
python/ray/_private/worker.py:414 Worker, :1227 init, :1826 shutdown).  ``init``
either starts a local cluster (head Node: GCS + nodelet subprocesses) or connects
to an existing one by GCS address; the driver embeds a CoreWorker either way.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple, Union

from ray_tpu._private.ids import JobID, NodeID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import RaySystemError

logger = logging.getLogger(__name__)

_global_worker = None
_global_core = None  # CoreWorker for *this* process (driver or task worker)
_init_lock = threading.RLock()


class Worker:
    """Driver-side runtime handle."""

    def __init__(self, core, node=None, namespace: str = ""):
        self.core = core
        self.node = node  # Node process supervisor if we started the cluster
        self.namespace = namespace
        self.connected = True

    @property
    def gcs_addr(self):
        return tuple(self.core.gcs_conn.peername() or ("", 0))


def global_worker() -> Worker:
    if _global_worker is None:
        raise RaySystemError(
            "ray_tpu.init() has not been called (or shutdown() already ran)")
    return _global_worker


def global_worker_core():
    """The process-local CoreWorker, if any (drivers and task workers)."""
    return _global_core


def set_global_core(core) -> None:
    global _global_core
    _global_core = core


def is_initialized() -> bool:
    return _global_worker is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: str = "",
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    _node_name: str = "",
) -> Worker:
    global _global_worker
    with _init_lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return _global_worker
            raise RuntimeError("ray_tpu.init() called twice; use ignore_reinit_error=True")

        from ray_tpu._private.core_worker import CoreWorker
        from ray_tpu._private.node import Node

        if address is None:
            # submitted-job drivers and `ray_tpu start` shells connect to the
            # running cluster via the env (reference: RAY_ADDRESS)
            address = os.environ.get("RAY_TPU_ADDRESS") or None
        # ray:// scheme = client mode (reference: Ray Client, util/client/):
        # the driver may be on a DIFFERENT machine; object data moves over
        # RPC instead of shared memory.
        client_mode = False
        if address and address.startswith("ray://"):
            client_mode = True
            address = address[len("ray://"):]
        node = None
        if address is None or address == "local":
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            node = Node(
                head=True,
                resources=res or None,
                object_store_memory=object_store_memory,
                node_name=_node_name,
            )
            node.start()
            gcs_addr = node.gcs_addr
            nodelet_addr = node.nodelet_addr
        else:
            host, port = address.rsplit(":", 1)
            gcs_addr = (host, int(port))
            nodelet_addr = _find_nodelet(gcs_addr)

        core = CoreWorker(
            mode="driver",
            gcs_addr=gcs_addr,
            nodelet_addr=nodelet_addr,
            remote_plasma=client_mode,
            namespace=namespace,
        )
        core.register_with_nodelet()
        core.register_driver(entrypoint=os.environ.get("_", ""))
        _global_worker = Worker(core, node=node, namespace=namespace)
        set_global_core(core)
        atexit.register(_atexit_shutdown)
        return _global_worker


def _find_nodelet(gcs_addr) -> Tuple[str, int]:
    """Connecting driver: attach to an alive nodelet registered in the GCS."""
    from ray_tpu._private import rpc

    io = rpc.EventLoopThread(name="rtpu-bootstrap")
    try:
        conn = io.run(rpc.connect(*gcs_addr, name="bootstrap"))
        deadline = time.monotonic() + 30
        while True:
            view = io.run(conn.call("get_cluster_view", None))
            alive = [n for n in view if n["alive"]]
            if alive:
                # Prefer a nodelet on this host.
                for n in alive:
                    if n["addr"][0] in ("127.0.0.1", "localhost"):
                        return tuple(n["addr"])
                return tuple(alive[0]["addr"])
            if time.monotonic() > deadline:
                raise RaySystemError("no alive nodes in the cluster")
            time.sleep(0.1)
    finally:
        io.stop()


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def shutdown() -> None:
    global _global_worker
    with _init_lock:
        w = _global_worker
        if w is None:
            return
        _global_worker = None
        set_global_core(None)
        try:
            w.core.shutdown()
        finally:
            if w.node is not None:
                w.node.stop()


# =========================================================== public verbs
def require_core():
    """The CoreWorker for this process; works in drivers AND task workers."""
    core = global_worker_core()
    if core is None:
        raise RaySystemError("ray_tpu runtime not initialized in this process")
    return core


def put(value: Any) -> ObjectRef:
    return require_core().put(value)


def get(refs: Union[ObjectRef, List[ObjectRef]], *, timeout: Optional[float] = None):
    core = require_core()
    if isinstance(refs, ObjectRef):
        return core.get([refs], timeout)[0]
    if not isinstance(refs, list):
        raise TypeError(f"ray.get expects an ObjectRef or list, got {type(refs)}")
    return core.get(refs, timeout)


def wait(refs: List[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("ray.wait expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError(f"num_returns={num_returns} > len(refs)={len(refs)}")
    return require_core().wait(refs, num_returns, timeout, fetch_local)


async def get_async(ref: ObjectRef):
    """Awaitable get for async actors and drivers."""
    import asyncio

    core = require_core()
    return await asyncio.wrap_future(core.as_future(ref))
