"""Live-stack capture for hang diagnosis (`ray_tpu stack`).

Counterpart of the reference's ``ray stack`` (reference:
python/ray/scripts/scripts.py `ray stack`, which shells out to py-spy).
Here every process captures its own Python thread stacks in-process via
``sys._current_frames()`` — zero external deps, works on any host — and the
payload rides the ordinary RPC plane: nodelet ``dump_stacks`` fans out to
its workers, the GCS proxies to any node, and the state API / CLI /
dashboard render the result.

Shared by the CoreWorker (worker + driver processes) and the nodelet so the
two sides can never disagree on the payload shape.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict, Optional


def capture_thread_stacks(
        task_by_thread: Optional[Dict[int, dict]] = None) -> list:
    """One entry per live Python thread: id, name, formatted stack, and —
    when ``task_by_thread`` maps the thread id to a running task — the
    owning task's id/name, so `ray_tpu stack TASK_ID` can point at the
    exact frame a stuck task is blocked in."""
    names = {t.ident: t.name for t in threading.enumerate()}
    task_by_thread = task_by_thread or {}
    out = []
    for tid, frame in sys._current_frames().items():
        task = task_by_thread.get(tid)
        out.append({
            "thread_id": tid,
            "thread_name": names.get(tid, "?"),
            "task_id": task.get("task_id") if task else None,
            "task_name": task.get("name") if task else None,
            "stack": "".join(traceback.format_stack(frame)),
        })
    return out


def format_stack_payload(payload: dict, indent: str = "  ") -> str:
    """Human-readable rendering of one process's dump (CLI + log surfaces)."""
    head = [f"{payload.get('kind', 'process')} pid={payload.get('pid')}"]
    if payload.get("worker_id"):
        head.append(f"worker={payload['worker_id'][:12]}")
    if payload.get("actor_id"):
        head.append(f"actor={payload['actor_id'][:12]}")
    lines = [" ".join(head)]
    for t in payload.get("running_tasks", []):
        lines.append(f"{indent}running task {t['task_id'][:16]} "
                     f"name={t['name']} elapsed={t['elapsed_s']:.1f}s")
    for t in payload.get("threads", []):
        owner = (f" [task {t['task_id'][:16]} {t['task_name']}]"
                 if t.get("task_id") else "")
        lines.append(f"{indent}thread {t['thread_name']} "
                     f"(id={t['thread_id']}){owner}")
        for ln in t["stack"].rstrip().splitlines():
            lines.append(f"{indent}{indent}{ln}")
    return "\n".join(lines)
