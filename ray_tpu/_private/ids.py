"""Unique identifiers for jobs, tasks, actors, objects, nodes, and placement groups.

TPU-native counterpart of the reference's binary ID scheme (reference:
src/ray/common/id.h; python/ray/_raylet.pyx BaseID hierarchy).  IDs are fixed-length
random byte strings with structured derivation: ObjectIDs embed the owning TaskID plus
a return/put index so ownership can be recovered from the ID alone, and ActorIDs embed
the JobID.  Unlike the reference we keep them pure-Python values (hashable, msgpack-
friendly); the hot paths that care about ID cost operate on the raw ``bytes``.
"""

from __future__ import annotations

import os
import random as _pyrandom

# Task/object IDs are minted on the submission hot path (one per `.remote()`);
# os.urandom there costs a getrandom(2) syscall per call (~25us measured).
# Uniqueness, not unpredictability, is the requirement — a per-process PRNG
# seeded from real entropy gives 64-bit-unique values at ~1us.  Workers are
# spawned (not forked), so every process re-seeds on import.
_uid_rng = _pyrandom.Random(
    int.from_bytes(os.urandom(16), "little") ^ (os.getpid() << 64))


def _fast_unique(n: int) -> bytes:
    return _uid_rng.getrandbits(n * 8).to_bytes(n, "little")

# Sizes (bytes). Reference uses 28-byte TaskID / JobID 4 / ActorID 16 / ObjectID 28.
JOB_ID_SIZE = 4
ACTOR_ID_UNIQUE_BYTES = 12
ACTOR_ID_SIZE = ACTOR_ID_UNIQUE_BYTES + JOB_ID_SIZE
TASK_ID_UNIQUE_BYTES = 8
TASK_ID_SIZE = TASK_ID_UNIQUE_BYTES + ACTOR_ID_SIZE
OBJECT_ID_INDEX_BYTES = 4
OBJECT_ID_SIZE = TASK_ID_SIZE + OBJECT_ID_INDEX_BYTES
NODE_ID_SIZE = 16
PLACEMENT_GROUP_ID_SIZE = 14
WORKER_ID_SIZE = 16

_MAX_INDEX = 2 ** (OBJECT_ID_INDEX_BYTES * 8) - 1


class BaseID:
    __slots__ = ("_binary", "_hash")
    SIZE = 0

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._binary = binary
        # IDs key every hot-path dict (ref counts, memory store, inflight
        # registries: ~16 hash lookups per task); bytes.__hash__ re-scans
        # the payload each time, so cache it once
        self._hash = hash(binary)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        if value >= 2 ** (JOB_ID_SIZE * 8) - 1:
            # The all-ones value is the nil sentinel.
            raise ValueError(f"job id out of range: {value}")
        return cls(value.to_bytes(JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(ACTOR_ID_UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[ACTOR_ID_UNIQUE_BYTES:])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        nil_actor = b"\xff" * ACTOR_ID_UNIQUE_BYTES + job_id.binary()
        return cls(_fast_unique(TASK_ID_UNIQUE_BYTES) + nil_actor)

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_fast_unique(TASK_ID_UNIQUE_BYTES) + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(b"\x00" * TASK_ID_UNIQUE_BYTES + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[TASK_ID_UNIQUE_BYTES:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    """An object id: owning TaskID + a 32-bit return/put index (little endian)."""

    SIZE = OBJECT_ID_SIZE

    @classmethod
    def from_task(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 0 <= index <= _MAX_INDEX:
            raise ValueError(f"object index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(OBJECT_ID_INDEX_BYTES, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:TASK_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._binary[TASK_ID_SIZE:], "little")

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE
