"""Option validation + normalization for tasks and actors.

Counterpart of the reference's option machinery (reference:
python/ray/_private/ray_option_utils.py).  Produces the resource dict and
normalized SchedulingStrategy consumed by TaskSpec.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.config import RayConfig
from ray_tpu._private.task_spec import SchedulingStrategy
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

TASK_DEFAULTS = {
    "num_cpus": 1.0,
    "num_tpus": 0.0,
    "num_gpus": 0.0,
    "resources": None,
    "num_returns": 1,
    "max_retries": None,   # None -> RayConfig.task_max_retries_default
    "retry_exceptions": False,
    "scheduling_strategy": None,
    "runtime_env": None,
    "name": None,
    "memory": None,
}

ACTOR_DEFAULTS = {
    "num_cpus": 1.0,
    "num_tpus": 0.0,
    "num_gpus": 0.0,
    "resources": None,
    "max_restarts": None,  # None -> RayConfig.actor_max_restarts_default
    "max_task_retries": 0,
    "max_concurrency": 1,
    "scheduling_strategy": None,
    "runtime_env": None,
    "name": None,
    "namespace": None,
    "lifetime": None,  # None | "detached"
    "memory": None,
}


def merge_options(defaults: Dict[str, Any], *layers: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = dict(defaults)
    for layer in layers:
        if not layer:
            continue
        for k, v in layer.items():
            if k not in defaults:
                raise ValueError(f"unknown option {k!r}; valid: {sorted(defaults)}")
            out[k] = v
    if out.get("runtime_env") is not None:
        from ray_tpu import runtime_env as renv

        # Reject unknown/unsupported fields at SUBMISSION, not on the worker.
        out["runtime_env"] = renv.validate(out["runtime_env"])
    # config-backed defaults resolve at merge time, so the cluster-wide
    # knobs apply without every call site knowing about them
    if "max_retries" in defaults and out.get("max_retries") is None:
        out["max_retries"] = RayConfig.task_max_retries_default
    if "max_restarts" in defaults and out.get("max_restarts") is None:
        out["max_restarts"] = RayConfig.actor_max_restarts_default
    return out


def resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    res: Dict[str, float] = {}
    if opts.get("num_cpus"):
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        from ray_tpu.accelerators import tpu_manager

        err = tpu_manager().validate_resource_request_quantity(opts["num_tpus"])
        if err:
            raise ValueError(err)
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        if k in ("CPU", "TPU", "GPU"):
            raise ValueError(f"pass {k} via num_{k.lower()}s, not resources=")
        res[k] = float(v)
    return res


def strategy_from_options(opts: Dict[str, Any]) -> SchedulingStrategy:
    s = opts.get("scheduling_strategy")
    if s is None or s == "DEFAULT":
        return SchedulingStrategy(kind="default")
    if s == "SPREAD":
        return SchedulingStrategy(kind="spread")
    if isinstance(s, PlacementGroupSchedulingStrategy):
        pg = s.placement_group
        return SchedulingStrategy(
            kind="placement_group",
            placement_group_id=pg.id,
            placement_group_bundle_index=s.placement_group_bundle_index,
            placement_group_capture_child_tasks=s.placement_group_capture_child_tasks,
        )
    if isinstance(s, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(kind="node_affinity", node_id=s.node_id, soft=s.soft)
    if isinstance(s, NodeLabelSchedulingStrategy):
        return SchedulingStrategy(kind="node_label",
                                  label_selector={"hard": dict(s.hard),
                                                  "soft": dict(s.soft)})
    raise ValueError(f"invalid scheduling_strategy: {s!r}")
