"""Label-aware Prometheus parsing + per-library metric views.

The scrape side (`_private/metrics.py`) renders registries to exposition
text; this module is the READ side: parse that text back into labeled
samples and fold them into the Serve/Data/Train summaries the dashboard
views, `ray_tpu summary serve|data|train`, and
`util.state.summarize_serve/data/train` all render (reference: the
dashboard's metrics module queries Prometheus for the ray_serve_*/
ray_data_* series; here the views aggregate the scrape directly so no
Prometheus server is required).

Dependency-free on purpose: the dashboard is a pure GCS/nodelet client and
must not import the driver-side worker module.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# (metric_name, labels, value)
Sample = Tuple[str, Dict[str, str], float]


def parse_prometheus(text: str) -> List[Sample]:
    """Parse exposition text into labeled samples (inverse of
    Registry.prometheus_text; label values are unescaped)."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            body, value_s = line.rsplit(None, 1)
            value = float(value_s)
        except ValueError:
            continue
        if "{" in body:
            name, _, rest = body.partition("{")
            labels = _parse_labels(rest.rstrip().rstrip("}"))
        else:
            name, labels = body, {}
        out.append((name, labels, value))
    return out


def _parse_labels(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        eq = s.find("=", i)
        if eq < 0 or eq + 1 >= n or s[eq + 1] != '"':
            break  # malformed tail; keep what parsed
        key = s[i:eq].strip().strip(",").strip()
        buf: List[str] = []
        k = eq + 2
        while k < n:
            c = s[k]
            if c == "\\" and k + 1 < n:
                nxt = s[k + 1]
                buf.append({"n": "\n"}.get(nxt, nxt))
                k += 2
                continue
            if c == '"':
                break
            buf.append(c)
            k += 1
        out[key] = "".join(buf)
        i = k + 1
        while i < n and s[i] in ", ":
            i += 1
    return out


def collect_samples(texts: Iterable[str],
                    exclude_sources: Sequence[str] = ()) -> List[Sample]:
    """Parse several scrape documents into one sample list.  A process's
    series appear on its nodelet's scrape tagged ``source=<proc>``;
    ``exclude_sources`` drops those copies so a caller that ALSO reads its
    own local registry (util.state does) never double counts itself."""
    excl = set(exclude_sources)
    out: List[Sample] = []
    for text in texts:
        for name, labels, value in parse_prometheus(text or ""):
            if excl and labels.get("source") in excl:
                continue
            out.append((name, labels, value))
    return out


# --------------------------------------------------------- fold helpers

_Key = Tuple[str, ...]


def _sum_by(samples: List[Sample], name: str,
            keys: Sequence[str]) -> Dict[_Key, float]:
    out: Dict[_Key, float] = {}
    for n, labels, v in samples:
        if n != name:
            continue
        k = tuple(labels.get(x, "") for x in keys)
        out[k] = out.get(k, 0.0) + v
    return out


def _max_by(samples: List[Sample], name: str,
            keys: Sequence[str]) -> Dict[_Key, float]:
    out: Dict[_Key, float] = {}
    for n, labels, v in samples:
        if n != name:
            continue
        k = tuple(labels.get(x, "") for x in keys)
        out[k] = max(out.get(k, v), v)
    return out


def _hist_by(samples: List[Sample], name: str,
             keys: Sequence[str]) -> Dict[_Key, Dict[str, float]]:
    """Fold a histogram's _bucket/_sum/_count series into per-key stats with
    bucket-interpolated percentiles: {key: {count, sum, mean, p50, p95,
    p99}}.  Series from several sources merge by summing buckets first."""
    buckets: Dict[_Key, Dict[float, float]] = {}
    sums = _sum_by(samples, name + "_sum", keys)
    counts = _sum_by(samples, name + "_count", keys)
    for n, labels, v in samples:
        if n != name + "_bucket":
            continue
        le_s = labels.get("le", "+Inf")
        le = float("inf") if le_s == "+Inf" else float(le_s)
        k = tuple(labels.get(x, "") for x in keys)
        per = buckets.setdefault(k, {})
        per[le] = per.get(le, 0.0) + v
    out: Dict[_Key, Dict[str, float]] = {}
    for k, count in counts.items():
        total = sums.get(k, 0.0)
        stats = {
            "count": count,
            "sum": total,
            "mean": (total / count) if count else 0.0,
        }
        per = buckets.get(k, {})
        for q in (0.5, 0.95, 0.99):
            stats[f"p{int(q * 100)}"] = _bucket_quantile(per, count, q)
        out[k] = stats
    return out


def _bucket_quantile(buckets: Dict[float, float], count: float,
                     q: float) -> float:
    """Prometheus-style histogram_quantile: linear interpolation inside the
    first bucket whose cumulative count crosses the target rank."""
    if not buckets or count <= 0:
        return 0.0
    target = q * count
    prev_le, prev_cum = 0.0, 0.0
    for le in sorted(buckets):
        cum = buckets[le]
        if cum >= target:
            if le == float("inf"):
                return prev_le  # open-ended top bucket: best known bound
            span = cum - prev_cum
            frac = ((target - prev_cum) / span) if span > 0 else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le


def _joined(keys: Iterable[_Key]) -> List[Tuple[str, _Key]]:
    return sorted(("/".join(k), k) for k in keys)


# ------------------------------------------------------------ serve view

def summarize_serve(samples: List[Sample]) -> Dict[str, Dict[str, float]]:
    """Per-deployment Serve view: {"app/deployment": {replicas, target,
    requests, errors, queue_depth, latency mean/p50/p95/p99 (s)}}."""
    keys = ("app", "deployment")
    req = _sum_by(samples, "ray_tpu_serve_request_total", keys)
    err = _sum_by(samples, "ray_tpu_serve_request_error_total", keys)
    queue = _sum_by(samples, "ray_tpu_serve_replica_queue_depth", keys)
    reps = _max_by(samples, "ray_tpu_serve_deployment_replicas", keys)
    target = _max_by(samples, "ray_tpu_serve_deployment_target_replicas", keys)
    lat = _hist_by(samples, "ray_tpu_serve_request_latency_seconds", keys)
    out: Dict[str, Dict[str, float]] = {}
    for joined, k in _joined(set(req) | set(err) | set(queue) | set(reps)
                             | set(target) | set(lat)):
        stats = lat.get(k, {})
        out[joined] = {
            "replicas": reps.get(k, 0.0),
            "target_replicas": target.get(k, 0.0),
            "requests": req.get(k, 0.0),
            "errors": err.get(k, 0.0),
            "queue_depth": queue.get(k, 0.0),
            "latency_mean_s": stats.get("mean", 0.0),
            "latency_p50_s": stats.get("p50", 0.0),
            "latency_p95_s": stats.get("p95", 0.0),
            "latency_p99_s": stats.get("p99", 0.0),
        }
    return out


# ------------------------------------------------------------- data view

def summarize_data(samples: List[Sample]) -> Dict[str, Dict]:
    """Data view: per-operator counters/queues plus per-pipeline byte budget
    state: {"operators": {"dataset/op": {...}}, "pipelines": {dataset:
    {buffered_bytes, backpressure}}}."""
    keys = ("dataset", "operator")
    rows = _sum_by(samples, "ray_tpu_data_rows_output_total", keys)
    blocks = _sum_by(samples, "ray_tpu_data_blocks_output_total", keys)
    tasks = _sum_by(samples, "ray_tpu_data_tasks_launched_total", keys)
    queue = _sum_by(samples, "ray_tpu_data_output_queue_blocks", keys)
    operators: Dict[str, Dict[str, float]] = {}
    for joined, k in _joined(set(rows) | set(blocks) | set(tasks)
                             | set(queue)):
        operators[joined] = {
            "rows": rows.get(k, 0.0),
            "blocks": blocks.get(k, 0.0),
            "tasks": tasks.get(k, 0.0),
            "output_queue_blocks": queue.get(k, 0.0),
        }
    buffered = _max_by(samples, "ray_tpu_data_buffered_bytes", ("dataset",))
    gated = _max_by(samples, "ray_tpu_data_backpressure", ("dataset",))
    pipelines = {
        k[0]: {"buffered_bytes": buffered.get(k, 0.0),
               "backpressure": gated.get(k, 0.0)}
        for k in set(buffered) | set(gated)
    }
    return {"operators": operators, "pipelines": pipelines}


# ------------------------------------------------------------ train view

# Values of the ray_tpu_train_gang_state gauge.
GANG_STATES = {"STARTING": 0.0, "RUNNING": 1.0, "FINISHED": 2.0,
               "FAILED": 3.0}
_GANG_NAMES = {v: k for k, v in GANG_STATES.items()}


def summarize_train(samples: List[Sample]) -> Dict[str, Dict]:
    """Per-experiment Train view: gang state/size, report()
    throughput counters, checkpoint-persist latency stats."""
    keys = ("experiment",)
    reports = _sum_by(samples, "ray_tpu_train_report_total", keys)
    rounds = _sum_by(samples, "ray_tpu_train_report_rounds_total", keys)
    state = _max_by(samples, "ray_tpu_train_gang_state", keys)
    workers = _max_by(samples, "ray_tpu_train_gang_workers", keys)
    skew = _max_by(samples, "ray_tpu_train_gang_step_skew", keys)
    ckpt = _hist_by(samples, "ray_tpu_train_checkpoint_persist_seconds", keys)
    # per-rank step heartbeats: derive skew directly from the rank gauges
    # too, so the view names stragglers even before (or without) the
    # driver-folded skew gauge landing on a scrape
    rank_steps = _max_by(samples, "ray_tpu_train_rank_step",
                         ("experiment", "rank"))
    steps_per_exp: Dict[_Key, List[float]] = {}
    for (exp, _rank), v in rank_steps.items():
        steps_per_exp.setdefault((exp,), []).append(v)
    out: Dict[str, Dict] = {}
    for k in set(reports) | set(rounds) | set(state) | set(workers) \
            | set(ckpt) | set(skew) | set(steps_per_exp):
        stats = ckpt.get(k, {})
        steps = steps_per_exp.get(k, [])
        derived_skew = (max(steps) - min(steps)) if len(steps) > 1 else 0.0
        out[k[0]] = {
            "gang_state": _GANG_NAMES.get(state.get(k, -1.0), "UNKNOWN"),
            "workers": workers.get(k, 0.0),
            "reports": reports.get(k, 0.0),
            "report_rounds": rounds.get(k, 0.0),
            "step": max(steps) if steps else 0.0,
            "step_skew": max(skew.get(k, 0.0), derived_skew),
            "checkpoints": stats.get("count", 0.0),
            "checkpoint_mean_s": stats.get("mean", 0.0),
            "checkpoint_p50_s": stats.get("p50", 0.0),
            "checkpoint_p95_s": stats.get("p95", 0.0),
        }
    return out


# ------------------------------------------------------------- llm view

def summarize_llm(samples: List[Sample]) -> Dict[str, Dict[str, float]]:
    """Per-engine LLM view: request/token counters, TTFT and inter-token
    latency percentiles, decode-batch occupancy, KV-page utilization,
    preemptions, queue depth and throughput — the serving-side signals the
    continuous-batching engine exports (ray_tpu_llm_* series)."""
    keys = ("engine",)
    req = _sum_by(samples, "ray_tpu_llm_requests_total", keys)
    ptoks = _sum_by(samples, "ray_tpu_llm_prompt_tokens_total", keys)
    toks = _sum_by(samples, "ray_tpu_llm_tokens_generated_total", keys)
    preempt = _sum_by(samples, "ray_tpu_llm_preemptions_total", keys)
    queue = _sum_by(samples, "ray_tpu_llm_queue_depth", keys)
    running = _sum_by(samples, "ray_tpu_llm_running_requests", keys)
    util = _max_by(samples, "ray_tpu_llm_kv_page_utilization", keys)
    tps = _max_by(samples, "ray_tpu_llm_tokens_per_second", keys)
    ttft = _hist_by(samples, "ray_tpu_llm_ttft_seconds", keys)
    itl = _hist_by(samples, "ray_tpu_llm_inter_token_seconds", keys)
    batch = _hist_by(samples, "ray_tpu_llm_decode_batch_size", keys)
    prefill = _sum_by(samples, "ray_tpu_llm_prefill_tokens_total", keys)
    hits = _sum_by(samples, "ray_tpu_llm_prefix_cache_hit_tokens_total",
                   keys)
    ppages = _max_by(samples, "ray_tpu_llm_prefix_cache_pages", keys)
    # shed carries a reason label; fold it away for the per-engine total
    shed = _sum_by(samples, "ray_tpu_llm_shed_total", keys)
    qwait = _hist_by(samples, "ray_tpu_llm_queue_wait_seconds", keys)
    out: Dict[str, Dict[str, float]] = {}
    for joined, k in _joined(set(req) | set(toks) | set(ptoks) | set(queue)
                             | set(running) | set(util) | set(tps)
                             | set(preempt) | set(ttft) | set(itl)
                             | set(batch) | set(prefill) | set(hits)
                             | set(ppages) | set(shed) | set(qwait)):
        t = ttft.get(k, {})
        i = itl.get(k, {})
        b = batch.get(k, {})
        q = qwait.get(k, {})
        pf = prefill.get(k, 0.0)
        hit = hits.get(k, 0.0)
        out[joined] = {
            "requests": req.get(k, 0.0),
            "prompt_tokens": ptoks.get(k, 0.0),
            "generated_tokens": toks.get(k, 0.0),
            "tokens_per_second": tps.get(k, 0.0),
            "ttft_mean_s": t.get("mean", 0.0),
            "ttft_p50_s": t.get("p50", 0.0),
            "ttft_p95_s": t.get("p95", 0.0),
            "ttft_p99_s": t.get("p99", 0.0),
            "itl_p50_s": i.get("p50", 0.0),
            "itl_p95_s": i.get("p95", 0.0),
            "itl_p99_s": i.get("p99", 0.0),
            "decode_batch_mean": b.get("mean", 0.0),
            "kv_page_utilization": util.get(k, 0.0),
            "preemptions": preempt.get(k, 0.0),
            "queue_depth": queue.get(k, 0.0),
            "running": running.get(k, 0.0),
            "prefill_tokens": pf,
            "prefix_hit_tokens": hit,
            "prefix_hit_rate": hit / (hit + pf) if (hit + pf) > 0 else 0.0,
            "prefix_cache_pages": ppages.get(k, 0.0),
            "shed": shed.get(k, 0.0),
            "queue_wait_p50_s": q.get("p50", 0.0),
            "queue_wait_p95_s": q.get("p95", 0.0),
        }
    return out


# ------------------------------------------------------------ rllib view

def summarize_rllib(samples: List[Sample]) -> Dict[str, Dict[str, float]]:
    """Per-job Podracer RL view: env-step/fragment throughput counters,
    fragment staleness (policy versions behind at consumption), learner
    update + gradient-allreduce latency, Sebulba inference-pool batch
    occupancy, published weight version and env-runner respawns
    (ray_tpu_rllib_* series)."""
    keys = ("job",)
    steps = _sum_by(samples, "ray_tpu_rllib_env_steps_total", keys)
    frags = _sum_by(samples, "ray_tpu_rllib_fragments_total", keys)
    infer_req = _sum_by(samples, "ray_tpu_rllib_inference_requests_total",
                        keys)
    restarts = _sum_by(samples, "ray_tpu_rllib_runner_restarts_total", keys)
    version = _max_by(samples, "ray_tpu_rllib_weight_version", keys)
    stale = _hist_by(samples, "ray_tpu_rllib_fragment_staleness", keys)
    upd = _hist_by(samples, "ray_tpu_rllib_learner_update_seconds", keys)
    ar = _hist_by(samples, "ray_tpu_rllib_learner_allreduce_seconds", keys)
    batch = _hist_by(samples, "ray_tpu_rllib_inference_batch_size", keys)
    out: Dict[str, Dict[str, float]] = {}
    for joined, k in _joined(set(steps) | set(frags) | set(infer_req)
                             | set(restarts) | set(version) | set(stale)
                             | set(upd) | set(ar) | set(batch)):
        s = stale.get(k, {})
        u = upd.get(k, {})
        a = ar.get(k, {})
        b = batch.get(k, {})
        out[joined] = {
            "env_steps": steps.get(k, 0.0),
            "fragments": frags.get(k, 0.0),
            "weight_version": version.get(k, 0.0),
            "staleness_mean": s.get("mean", 0.0),
            "staleness_p50": s.get("p50", 0.0),
            "staleness_p95": s.get("p95", 0.0),
            "updates": u.get("count", 0.0),
            "update_mean_s": u.get("mean", 0.0),
            "update_p95_s": u.get("p95", 0.0),
            "allreduce_mean_s": a.get("mean", 0.0),
            "allreduce_p95_s": a.get("p95", 0.0),
            "inference_requests": infer_req.get(k, 0.0),
            "inference_batch_mean": b.get("mean", 0.0),
            "inference_batch_p95": b.get("p95", 0.0),
            "runner_restarts": restarts.get(k, 0.0),
        }
    return out


# --------------------------------------------------- dashboard history

def history_point(samples: List[Sample]) -> Dict[str, Dict]:
    """Compact per-scrape library snapshot for the dashboard ring buffer —
    only the fields the page turns into sparklines (cumulative counters are
    recorded raw; the page differentiates successive samples into rates)."""
    serve = {
        k: {"requests": v["requests"], "queue": v["queue_depth"],
            "replicas": v["replicas"]}
        for k, v in summarize_serve(samples).items()
    }
    data = {
        k: {"rows": v["rows"], "queue": v["output_queue_blocks"]}
        for k, v in summarize_data(samples)["operators"].items()
    }
    train = {
        k: {"reports": v["reports"], "workers": v["workers"]}
        for k, v in summarize_train(samples).items()
    }
    llm = {
        k: {"tokens": v["generated_tokens"], "queue": v["queue_depth"],
            "running": v["running"]}
        for k, v in summarize_llm(samples).items()
    }
    rllib = {
        k: {"env_steps": v["env_steps"], "fragments": v["fragments"],
            "version": v["weight_version"]}
        for k, v in summarize_rllib(samples).items()
    }
    return {"serve": serve, "data": data, "train": train, "llm": llm,
            "rllib": rllib}
