"""ray_tpu.serve — model serving on the actor runtime.

TPU-native counterpart of Ray Serve (reference: python/ray/serve/api.py —
@serve.deployment :244, serve.run :510): a controller actor reconciles
declarative applications into replica actors; DeploymentHandles route
requests via power-of-two-choices; an aiohttp proxy serves HTTP; @serve.batch
shapes traffic into MXU-friendly batches.

Usage:
    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x):
            return ...

    app = Model.bind()
    handle = serve.run(app, name="app")
    handle.remote(x).result()
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "deployment", "run", "delete", "shutdown", "status",
    "get_deployment_handle", "get_app_handle", "batch", "start",
    "Deployment", "Application", "AutoscalingConfig", "DeploymentConfig",
    "DeploymentHandle", "DeploymentResponse",
    "multiplexed", "get_multiplexed_model_id",
]


class Deployment:
    """A decorated user class plus its config; .bind() produces an
    Application node (reference: serve/deployment.py Deployment)."""

    def __init__(self, cls, name: str, config: DeploymentConfig):
        self._cls = cls
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[dict] = None,
                autoscaling_config=None) -> "Deployment":
        import dataclasses

        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if autoscaling_config is not None:
            cfg.autoscaling_config = autoscaling_config
            cfg.__post_init__()
        return Deployment(self._cls, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


class Application:
    """Bound deployment graph node.  Init args may contain other Applications
    (composition): they deploy as sibling deployments and the argument becomes
    a DeploymentHandle (reference: serve build/bind DAG)."""

    def __init__(self, deployment: Deployment, args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _collect(self, out: Dict[str, "Application"]):
        if self.deployment.name in out:
            if out[self.deployment.name] is not self:
                raise ValueError(
                    f"duplicate deployment name {self.deployment.name!r}")
            return
        out[self.deployment.name] = self
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                a._collect(out)


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config=None,
               health_check_period_s: float = 1.0,
               health_check_timeout_s: float = 10.0):
    """Class decorator declaring a deployment (reference: serve/api.py:244)."""

    def deco(cls):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {},
            autoscaling_config=autoscaling_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
        )
        return Deployment(cls, name or cls.__name__, cfg)

    if _cls is not None:
        return deco(_cls)
    return deco


def _app_specs(app: Application, app_name: str) -> List[dict]:
    import cloudpickle

    nodes: Dict[str, Application] = {}
    app._collect(nodes)
    specs = []
    for dname, node in nodes.items():
        args = tuple(
            DeploymentHandle(app_name, a.deployment.name)
            if isinstance(a, Application) else a for a in node.args)
        kwargs = {k: (DeploymentHandle(app_name, v.deployment.name)
                      if isinstance(v, Application) else v)
                  for k, v in node.kwargs.items()}
        blob = cloudpickle.dumps(node.deployment._cls)
        version = hashlib.sha1(
            blob + cloudpickle.dumps((args, kwargs, node.deployment.config))
        ).hexdigest()
        specs.append({
            "name": dname,
            "serialized_cls": blob,
            "init_args": args,
            "init_kwargs": kwargs,
            "config": node.deployment.config,
            "version": version,
        })
    return specs


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/",
        _blocking: bool = True) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment
    (reference: serve/api.py:510)."""
    from ray_tpu.serve._controller import get_controller

    ctrl = get_controller(create=True)
    specs = _app_specs(target, name)
    ray_tpu.get(ctrl.deploy_application.remote(
        name, specs, target.deployment.name, route_prefix), timeout=120)
    handle = DeploymentHandle(name, target.deployment.name)
    if _blocking:
        handle._target.get_replicas()  # wait until a replica serves
    return handle


def start(http_host: str = "127.0.0.1", http_port: int = 8000,
          grpc_port: Optional[int] = None) -> int:
    """Ensure the proxy is up; returns the bound HTTP port.  Pass
    ``grpc_port`` (0 = ephemeral) to also serve the gRPC ingress
    (reference: gRPCProxy, proxy.py:545); read the bound gRPC port with
    ``grpc_ingress_port()``."""
    from ray_tpu.serve._controller import get_controller

    ctrl = get_controller(create=True)
    return ray_tpu.get(
        ctrl.ensure_proxy.remote(http_host, http_port, grpc_port),
        timeout=60)


def grpc_ingress_port() -> Optional[int]:
    """The bound gRPC ingress port, or None when gRPC is not enabled."""
    from ray_tpu.serve._controller import get_controller

    return ray_tpu.get(get_controller().proxy_grpc_port.remote(), timeout=30)


def delete(name: str) -> None:
    from ray_tpu.serve._controller import get_controller

    ctrl = get_controller()
    ray_tpu.get(ctrl.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    from ray_tpu.serve._controller import CONTROLLER_NAME, get_controller

    try:
        ctrl = get_controller()
    except RuntimeError:
        return
    ray_tpu.get(ctrl.shutdown.remote(), timeout=60)
    ray_tpu.kill(ctrl)


def status() -> Dict[str, Any]:
    from ray_tpu.serve._controller import get_controller

    return ray_tpu.get(get_controller().status.remote(), timeout=60)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    from ray_tpu.serve._controller import get_controller

    ingress = ray_tpu.get(
        get_controller().get_ingress.remote(app_name), timeout=60)
    if ingress is None:
        raise ValueError(f"no application named {app_name!r}")
    return DeploymentHandle(app_name, ingress)
