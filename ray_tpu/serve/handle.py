"""DeploymentHandle: the Python-native request path into a deployment.

Reference: python/ray/serve/handle.py (DeploymentHandle :729,
DeploymentResponse :801) + the router's power-of-two-choices replica pick
(python/ray/serve/_private/replica_scheduler/pow_2_scheduler.py:51).

The handle is address-only (app + deployment names) so it pickles freely into
other deployments (model composition) and driver code; the replica set is
fetched from the controller lazily and refreshed on a period or on failure.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayActorError

_REFRESH_PERIOD_S = 2.0


class DeploymentResponse:
    """Future for one request (reference: DeploymentResponse).  Chains into
    other handle calls by passing the underlying ObjectRef."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        from ray_tpu._private.worker import get_async

        return get_async(self._ref).__await__()


class _Router:
    """Per-handle replica picker: power-of-two-choices on locally tracked
    in-flight counts (reference: pow_2_scheduler.py:51 — two random replicas,
    route to the less loaded).  With a multiplexed model id, replicas that
    already hold the model are preferred (reference: pow-2 scheduler's
    multiplexed-model candidate ranking) — a cold load costs seconds of HBM
    traffic; an affinity hit costs nothing."""

    def __init__(self):
        self._inflight: Dict[bytes, int] = {}
        self._lock = threading.Lock()

    def pick(self, replicas: List[Any], model_id: str = "",
             model_map: Optional[Dict[str, List[str]]] = None):
        if not replicas:
            raise RuntimeError("no replicas available")
        if model_id and model_map:
            holders = [r for r in replicas
                       if model_id in model_map.get(r._actor_id.hex(), ())]
            if holders:
                replicas = holders
        with self._lock:
            if len(replicas) == 1:
                choice = replicas[0]
            else:
                a, b = random.sample(replicas, 2)
                ka, kb = a._actor_id.binary(), b._actor_id.binary()
                choice = a if self._inflight.get(ka, 0) <= self._inflight.get(kb, 0) else b
            k = choice._actor_id.binary()
            self._inflight[k] = self._inflight.get(k, 0) + 1
            return choice

    def done(self, replica) -> None:
        with self._lock:
            k = replica._actor_id.binary()
            n = self._inflight.get(k, 0)
            if n <= 1:
                self._inflight.pop(k, None)
            else:
                self._inflight[k] = n - 1


class _DeploymentTarget:
    """Process-shared per-(app, deployment) routing state: ONE router, ONE
    replica/model-map cache, ONE long-poll listener thread — shared by every
    handle (``options()`` clones included), so per-request
    ``handle.options(multiplexed_model_id=...)`` never multiplies threads or
    resets affinity state (reference: the router/LongPollClient is per
    process, serve/_private/router.py)."""

    def __init__(self, app: str, deployment: str):
        self.app = app
        self.deployment = deployment
        self.router = _Router()
        self.replicas: List[Any] = []
        self.model_map: Dict[str, List[str]] = {}
        self.fetched_at = 0.0
        self.lock = threading.Lock()
        self.listener: Optional[threading.Thread] = None

    # ---------------------------------------------- long-poll listener
    def ensure_listener(self) -> None:
        """Config-push channel (reference: long_poll.py LongPollClient):
        replica-set and multiplex-map updates arrive the moment the
        controller publishes them — the periodic refresh in get_replicas is
        only the fallback when the listener thread is unhealthy."""
        with self.lock:
            if self.listener is not None and self.listener.is_alive():
                return
            self.listener = threading.Thread(
                target=self._listen_loop, daemon=True,
                name=f"serve-longpoll-{self.app}/{self.deployment}")
            self.listener.start()

    def _controller(self):
        from ray_tpu.serve._controller import get_controller

        return get_controller()

    def _listen_loop(self) -> None:
        rkey = f"replicas::{self.app}/{self.deployment}"
        mkey = f"multiplex::{self.app}/{self.deployment}"
        versions = {rkey: 0, mkey: 0}
        ctrl_id = None
        while True:
            try:
                ctrl = self._controller()
                if ctrl._actor_id != ctrl_id:
                    # a NEW controller (serve restarted) numbers versions
                    # from scratch: keeping the old snapshot would park the
                    # listen forever above the new counters
                    ctrl_id = ctrl._actor_id
                    versions = {rkey: 0, mkey: 0}
                out = ray_tpu.get(
                    ctrl.listen_for_change.remote(dict(versions), 30.0),
                    timeout=45)
            except Exception:
                time.sleep(1.0)
                continue
            for key, entry in (out or {}).items():
                versions[key] = entry["version"]
                with self.lock:
                    if key == rkey:
                        # empty sets apply too: after serve.delete the
                        # handle must fail fast, not route to killed
                        # replicas from a stale cache
                        self.replicas = list(entry["value"])
                        self.fetched_at = time.monotonic()
                    elif key == mkey:
                        self.model_map = dict(entry["value"])

    def get_replicas(self, force: bool = False) -> List[Any]:
        self.ensure_listener()
        now = time.monotonic()
        # with a live push listener the cache is authoritative; the short
        # period only kicks in as a polling FALLBACK when the listener died
        period = 30.0 if (self.listener is not None
                          and self.listener.is_alive()) \
            else _REFRESH_PERIOD_S
        with self.lock:
            if (not force and self.replicas
                    and now - self.fetched_at < period):
                return self.replicas
        ctrl = self._controller()
        deadline = time.monotonic() + 30.0
        while True:
            replicas = ray_tpu.get(
                ctrl.get_replicas.remote(self.app, self.deployment),
                timeout=30)
            if replicas:
                with self.lock:
                    self.replicas = replicas
                    self.fetched_at = time.monotonic()
                return replicas
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self.app}/{self.deployment}")
            time.sleep(0.1)


_targets: Dict[tuple, _DeploymentTarget] = {}
_targets_lock = threading.Lock()


def _get_target(app: str, deployment: str) -> _DeploymentTarget:
    key = (app, deployment)
    with _targets_lock:
        t = _targets.get(key)
        if t is None:
            t = _targets[key] = _DeploymentTarget(app, deployment)
        return t


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = ""):
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._target = _get_target(app_name, deployment_name)

    # handles pickle into other deployments: resolve the process-local
    # target on the receiving side
    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment, self._method,
                                   self._model_id))

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """reference: handle.options(method_name=...,
        multiplexed_model_id=...) — serve/handle.py:729.  Clones share the
        underlying router/listener (cheap, call per request)."""
        return DeploymentHandle(
            self._app, self._deployment,
            self._method if method_name is None else method_name,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id)

    @property
    def method(self):
        return self._method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Chain composition: unwrap nested responses into their refs so the
        # downstream replica awaits the upstream result, not a wrapper.
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                      else v) for k, v in kwargs.items()}
        return self._call(args, kwargs, retries=2)

    def _call(self, args, kwargs, retries: int) -> "_TrackedResponse":
        t = self._target
        replicas = t.get_replicas(force=retries < 2)
        with t.lock:
            model_map = dict(t.model_map) if self._model_id else None
        replica = t.router.pick(replicas, self._model_id, model_map)
        ref = replica.handle_request.remote(
            self._method, args, kwargs,
            multiplexed_model_id=self._model_id)
        # Router accounting keyed to RESULT ARRIVAL (memory-store ready
        # callback), not to result() being called — fire-and-forget and
        # awaited responses must release in-flight slots too.
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.require_core()
        released = {"done": False}

        def release():
            if not released["done"]:
                released["done"] = True
                t.router.done(replica)

        if core.memory_store.add_ready_callback(ref.oid, release):
            release()  # already completed
        return _TrackedResponse(ref, self, args, kwargs, retries,
                                replica=replica)


class _TrackedResponse(DeploymentResponse):
    """Response that retries through a FRESH replica when the picked one died
    before answering (the controller replaces dead replicas; the handle's
    cached replica set can be up to _REFRESH_PERIOD_S stale)."""

    def __init__(self, ref, handle: "DeploymentHandle", args, kwargs,
                 retries: int, replica=None):
        super().__init__(ref)
        self._handle = handle
        self._args = args
        self._kwargs = kwargs
        self._retries = retries
        self._replica = replica

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            out = super().result(timeout_s)
        except RayActorError:
            if self._retries <= 0:
                raise
            retry = self._handle._call(self._args, self._kwargs,
                                       self._retries - 1)
            return retry.result(timeout_s)
        return self._unwrap_stream(out)

    def _unwrap_stream(self, out):
        """Generator-returning deployments answer with a StreamHeader: hand
        the caller a pull-based ResponseStream bound to the SAME replica
        that holds the generator (streams are replica-affine; a retry
        through another replica could not resume them)."""
        from ray_tpu.serve._streaming import ResponseStream, StreamHeader

        if isinstance(out, StreamHeader) and self._replica is not None:
            return ResponseStream(self._replica, out.stream_id)
        return out
