"""DeploymentHandle: the Python-native request path into a deployment.

Reference: python/ray/serve/handle.py (DeploymentHandle :729,
DeploymentResponse :801) + the router's power-of-two-choices replica pick
(python/ray/serve/_private/replica_scheduler/pow_2_scheduler.py:51).

The handle is address-only (app + deployment names) so it pickles freely into
other deployments (model composition) and driver code; the replica set is
fetched from the controller lazily and refreshed on a period or on failure.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayActorError

_REFRESH_PERIOD_S = 2.0


class DeploymentResponse:
    """Future for one request (reference: DeploymentResponse).  Chains into
    other handle calls by passing the underlying ObjectRef."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        from ray_tpu._private.worker import get_async

        return get_async(self._ref).__await__()


class _Router:
    """Per-handle replica picker: power-of-two-choices on locally tracked
    in-flight counts (reference: pow_2_scheduler.py:51 — two random replicas,
    route to the less loaded)."""

    def __init__(self):
        self._inflight: Dict[bytes, int] = {}
        self._lock = threading.Lock()

    def pick(self, replicas: List[Any]):
        if not replicas:
            raise RuntimeError("no replicas available")
        with self._lock:
            if len(replicas) == 1:
                choice = replicas[0]
            else:
                a, b = random.sample(replicas, 2)
                ka, kb = a._actor_id.binary(), b._actor_id.binary()
                choice = a if self._inflight.get(ka, 0) <= self._inflight.get(kb, 0) else b
            k = choice._actor_id.binary()
            self._inflight[k] = self._inflight.get(k, 0) + 1
            return choice

    def done(self, replica) -> None:
        with self._lock:
            k = replica._actor_id.binary()
            n = self._inflight.get(k, 0)
            if n <= 1:
                self._inflight.pop(k, None)
            else:
                self._inflight[k] = n - 1


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 method_name: str = "__call__"):
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._init_local()

    def _init_local(self):
        self._router = _Router()
        self._replicas: List[Any] = []
        self._fetched_at = 0.0
        self._lock = threading.Lock()

    # handles pickle into other deployments: drop the live local state
    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment, self._method))

    def options(self, *, method_name: str) -> "DeploymentHandle":
        h = DeploymentHandle(self._app, self._deployment, method_name)
        return h

    @property
    def method(self):
        return self._method

    def _controller(self):
        from ray_tpu.serve._controller import get_controller

        return get_controller()

    def _get_replicas(self, force: bool = False) -> List[Any]:
        now = time.monotonic()
        with self._lock:
            if (not force and self._replicas
                    and now - self._fetched_at < _REFRESH_PERIOD_S):
                return self._replicas
        ctrl = self._controller()
        deadline = time.monotonic() + 30.0
        while True:
            replicas = ray_tpu.get(
                ctrl.get_replicas.remote(self._app, self._deployment),
                timeout=30)
            if replicas:
                with self._lock:
                    self._replicas = replicas
                    self._fetched_at = time.monotonic()
                return replicas
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self._app}/{self._deployment}")
            time.sleep(0.1)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Chain composition: unwrap nested responses into their refs so the
        # downstream replica awaits the upstream result, not a wrapper.
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                      else v) for k, v in kwargs.items()}
        return self._call(args, kwargs, retries=2)

    def _call(self, args, kwargs, retries: int) -> "_TrackedResponse":
        replicas = self._get_replicas(force=retries < 2)
        replica = self._router.pick(replicas)
        ref = replica.handle_request.remote(self._method, args, kwargs)
        # Router accounting keyed to RESULT ARRIVAL (memory-store ready
        # callback), not to result() being called — fire-and-forget and
        # awaited responses must release in-flight slots too.
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.require_core()
        released = {"done": False}

        def release():
            if not released["done"]:
                released["done"] = True
                self._router.done(replica)

        if core.memory_store.add_ready_callback(ref.oid, release):
            release()  # already completed
        return _TrackedResponse(ref, self, args, kwargs, retries)


class _TrackedResponse(DeploymentResponse):
    """Response that retries through a FRESH replica when the picked one died
    before answering (the controller replaces dead replicas; the handle's
    cached replica set can be up to _REFRESH_PERIOD_S stale)."""

    def __init__(self, ref, handle: "DeploymentHandle", args, kwargs,
                 retries: int):
        super().__init__(ref)
        self._handle = handle
        self._args = args
        self._kwargs = kwargs
        self._retries = retries

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            return super().result(timeout_s)
        except RayActorError:
            if self._retries <= 0:
                raise
            retry = self._handle._call(self._args, self._kwargs,
                                       self._retries - 1)
            return retry.result(timeout_s)
