"""Serve library metrics (reference: the ray_serve_* series emitted by
serve/_private/replica.py, proxy.py and autoscaling_state.py; exported here
as ray_tpu_serve_* on every node's /metrics scrape).

One lazily-built singleton set per process: replicas, the proxy and the
controller each record into their own process-local registry, their
CoreWorker pushes snapshots to the nodelet, and the per-node scrape merges
them (distinct ``source`` labels keep per-replica series apart; the view
layer in `_private/metrics_view.py` sums them back per deployment).
"""

from __future__ import annotations

import threading
from typing import Dict

from ray_tpu._private import metrics as M

# Request latencies: sub-ms cache hits up to multi-second model generations.
REQUEST_LATENCY_BOUNDARIES = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_lock = threading.Lock()
_metrics: Dict[str, M.Metric] = {}


def serve_metrics() -> Dict[str, M.Metric]:
    """The process-local Serve metric set (idempotent; re-instantiation by
    name adopts existing storage, so the lock only avoids wasted work)."""
    global _metrics
    if not _metrics:
        with _lock:
            if not _metrics:
                _metrics = {
                    "requests": M.Counter(
                        "serve_request_total",
                        "requests handled, per app/deployment"),
                    "request_errors": M.Counter(
                        "serve_request_error_total",
                        "requests that raised, per app/deployment"),
                    "latency": M.Histogram(
                        "serve_request_latency_seconds",
                        "replica-side request latency, per app/deployment",
                        boundaries=REQUEST_LATENCY_BOUNDARIES),
                    "queue_depth": M.Gauge(
                        "serve_replica_queue_depth",
                        "requests in flight on a replica (per-source "
                        "series sum to deployment queue depth)"),
                    "replicas": M.Gauge(
                        "serve_deployment_replicas",
                        "running replicas, per app/deployment"),
                    "target_replicas": M.Gauge(
                        "serve_deployment_target_replicas",
                        "reconcile target replica count, per "
                        "app/deployment"),
                    "autoscale_decisions": M.Counter(
                        "serve_autoscale_decisions_total",
                        "committed autoscaler scale decisions, per "
                        "app/deployment/direction"),
                    "streams": M.Counter(
                        "serve_streams_total",
                        "streaming (generator) responses started, per "
                        "app/deployment"),
                    "ingress_requests": M.Counter(
                        "serve_ingress_requests_total",
                        "proxy ingress requests, per protocol/status"),
                    "ingress_latency": M.Histogram(
                        "serve_ingress_latency_seconds",
                        "proxy ingress end-to-end latency, per protocol",
                        boundaries=REQUEST_LATENCY_BOUNDARIES),
                }
    return _metrics
