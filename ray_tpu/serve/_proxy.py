"""HTTP proxy: routes requests to application ingress deployments.

Reference: python/ray/serve/_private/proxy.py (HTTPProxy :766, ProxyActor
:1139), condensed to the aiohttp equivalent: longest-prefix route match,
JSON/text body handling, handle-based fan-in to replicas.  gRPC ingress is
out of scope (the reference's gRPCProxy); the Python handle path covers
in-cluster composition.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, Optional

import ray_tpu

logger = logging.getLogger(__name__)


@ray_tpu.remote(num_cpus=0)
class ProxyActor:
    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._site = None
        self._handles: Dict[str, object] = {}

    async def ready(self) -> int:
        """Start the aiohttp server; returns the bound port."""
        if self._site is not None:
            return self._port
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._port)
        await site.start()
        self._site = site
        # port 0 -> discover the bound port
        for sock in site._server.sockets:  # type: ignore[union-attr]
            self._port = sock.getsockname()[1]
            break
        logger.info("serve proxy listening on %s:%d", self._host, self._port)
        return self._port

    async def _handle(self, request):
        """aiohttp handler — runs on the worker's IO loop, so everything that
        touches the runtime (controller lookup, handle routing, get) is
        offloaded to executor threads where blocking calls are legal."""
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        body: object
        if request.can_read_body:
            raw = await request.read()
            if request.content_type == "application/json":
                body = json.loads(raw) if raw else None
            else:
                body = raw.decode() if raw else ""
        else:
            body = None
        loop = asyncio.get_event_loop()
        try:
            out = await loop.run_in_executor(
                None, self._route_and_call, path, body)
        except LookupError:
            return web.Response(status=404, text="no route")
        except Exception as e:
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(out, (dict, list)):
            return web.json_response(out)
        if isinstance(out, bytes):
            return web.Response(body=out)
        return web.Response(text=str(out))

    def _route_and_call(self, path: str, body):
        from ray_tpu.serve._controller import get_controller
        from ray_tpu.serve.handle import DeploymentHandle

        ctrl = get_controller()
        routes = ray_tpu.get(ctrl.get_routes.remote(), timeout=30)
        # longest matching prefix wins (reference: proxy route resolution)
        best = None
        for prefix, app_name in routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, app_name)
        if best is None:
            raise LookupError(path)
        app_name = best[1]
        # keyed by (app, ingress): a redeploy can change the ingress
        # deployment, and a handle cached on app name alone would route 500s
        ingress = ray_tpu.get(ctrl.get_ingress.remote(app_name), timeout=30)
        key = (app_name, ingress)
        handle = self._handles.get(key)
        if handle is None:
            handle = DeploymentHandle(app_name, ingress)
            self._handles[key] = handle
        return handle.remote(body).result(60.0)
