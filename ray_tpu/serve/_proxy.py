"""HTTP + gRPC proxies: route requests to application ingress deployments.

Reference: python/ray/serve/_private/proxy.py (HTTPProxy :766, gRPCProxy
:545, ProxyActor :1139), condensed: longest-prefix HTTP route match,
JSON/text body handling, and a proto-less gRPC ingress — a generic handler
accepts ``/{application}/{Method}`` unary calls with raw request bytes, so
any grpc client can call a deployment without compiled stubs::

    ch = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
    call = ch.unary_unary("/myapp/Predict")   # bytes in, bytes out
    reply = call(b"payload")

Both ingresses share the same DeploymentHandle cache, so HTTP and gRPC
traffic flow through ONE power-of-two-choices router per (app, ingress).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Dict, Optional

import ray_tpu

logger = logging.getLogger(__name__)


def _shed_cause(e: BaseException):
    """Unwrap admission-control sheds: the deployment raises RequestShed,
    which crosses the replica boundary either as an instance-of-cause
    hybrid or as a RayTaskError carrying it in ``cause``."""
    from ray_tpu.exceptions import RequestShed

    # prefer the pristine cause: an as_instanceof_cause hybrid IS a
    # RequestShed but carries task-wrapper args, not the shed's
    cause = getattr(e, "cause", None)
    if isinstance(cause, RequestShed):
        return cause
    return e if isinstance(e, RequestShed) else None


@ray_tpu.remote(num_cpus=0)
class ProxyActor:
    def __init__(self, host: str, port: int, grpc_port: Optional[int] = None):
        self._host = host
        self._port = port
        self._grpc_port = grpc_port
        self._grpc_server = None
        self._site = None
        self._handles: Dict[str, object] = {}
        # routes cache fed by the controller's long-poll channel: route
        # changes arrive as pushes instead of a control-plane RPC per
        # request (reference: proxy's LongPollClient on route_table)
        self._routes: Optional[Dict[str, str]] = None
        self._routes_listener = None
        from ray_tpu.serve._metrics import serve_metrics

        self._metrics = serve_metrics()

    def _observe_ingress(self, protocol: str, status: str,
                         start: float) -> None:
        self._metrics["ingress_requests"].inc(
            1, {"protocol": protocol, "status": status})
        self._metrics["ingress_latency"].observe(
            time.perf_counter() - start, {"protocol": protocol})

    async def ready(self) -> int:
        """Start the aiohttp server (and the gRPC server when configured);
        returns the bound HTTP port."""
        if self._site is not None:
            return self._port
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._port)
        await site.start()
        self._site = site
        # port 0 -> discover the bound port
        for sock in site._server.sockets:  # type: ignore[union-attr]
            self._port = sock.getsockname()[1]
            break
        logger.info("serve proxy listening on %s:%d", self._host, self._port)
        if self._grpc_port is not None:
            await self._start_grpc()
        return self._port

    async def grpc_port(self) -> Optional[int]:
        return self._grpc_port

    async def enable_grpc(self, grpc_port: int) -> int:
        """Start the gRPC ingress on an already-running proxy."""
        if self._grpc_server is None:
            self._grpc_port = grpc_port
            await self._start_grpc()
        return self._grpc_port

    # ------------------------------------------------------------- gRPC
    async def _start_grpc(self) -> None:
        import grpc

        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                method = details.method  # "/{app}/{Method}"

                async def unary(request: bytes, context):
                    return await proxy._grpc_call(method, request, context)

                return grpc.unary_unary_rpc_method_handler(unary)

        server = grpc.aio.server()
        server.add_generic_rpc_handlers((_Generic(),))
        self._grpc_port = server.add_insecure_port(
            f"{self._host}:{self._grpc_port}")
        await server.start()
        self._grpc_server = server
        logger.info("serve gRPC ingress listening on %s:%d",
                    self._host, self._grpc_port)

    async def _grpc_call(self, method: str, request: bytes, context) -> bytes:
        import grpc

        parts = method.strip("/").split("/", 1)
        app_name = parts[0]
        loop = asyncio.get_event_loop()
        start = time.perf_counter()
        try:
            out = await loop.run_in_executor(
                None, self._call_app, app_name, request)
        except LookupError:
            self._observe_ingress("grpc", "not_found", start)
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no application {app_name!r}")
        except Exception as e:
            shed = _shed_cause(e)
            if shed is not None:
                self._observe_ingress("grpc", "resource_exhausted", start)
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                    str(shed))
            self._observe_ingress("grpc", "error", start)
            await context.abort(grpc.StatusCode.INTERNAL,
                                f"{type(e).__name__}: {e}")
        from ray_tpu.serve._streaming import ResponseStream

        if isinstance(out, ResponseStream):
            # unary gRPC has no chunk framing: drain and reply once
            # (streaming ingress is the HTTP/SSE path)
            out = await loop.run_in_executor(None, lambda: list(out))
        self._observe_ingress("grpc", "ok", start)
        if isinstance(out, bytes):
            return out
        if isinstance(out, str):
            return out.encode()
        return json.dumps(out).encode()

    async def _handle(self, request):
        """aiohttp handler — runs on the worker's IO loop, so everything that
        touches the runtime (controller lookup, handle routing, get) is
        offloaded to executor threads where blocking calls are legal."""
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        body: object
        if request.can_read_body:
            raw = await request.read()
            if request.content_type == "application/json":
                body = json.loads(raw) if raw else None
            else:
                body = raw.decode() if raw else ""
        else:
            body = None
        loop = asyncio.get_event_loop()
        start = time.perf_counter()
        try:
            out = await loop.run_in_executor(
                None, self._route_and_call, path, body)
        except LookupError:
            self._observe_ingress("http", "404", start)
            return web.Response(status=404, text="no route")
        except Exception as e:
            shed = _shed_cause(e)
            if shed is not None:
                return self._shed_response(request, shed, start)
            self._observe_ingress("http", "500", start)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        from ray_tpu.serve._streaming import ResponseStream

        if isinstance(out, ResponseStream):
            return await self._stream_response(
                request, out, start,
                retry=lambda: self._route_and_call(path, body))
        self._observe_ingress("http", "200", start)
        if isinstance(out, (dict, list)):
            return web.json_response(out)
        if isinstance(out, bytes):
            return web.Response(body=out)
        return web.Response(text=str(out))

    def _shed_response(self, request, shed, start):
        """Admission-control shed: 429 + ``Retry-After``, never a hang.
        Clients that asked for SSE get the refusal as a terminal
        ``event: error`` frame (same shape streams use for mid-stream
        failures) so one parser handles both."""
        from aiohttp import web

        self._observe_ingress("http", "429", start)
        retry_after = max(1, int(-(-shed.retry_after_s // 1)))  # ceil
        payload = {"error": "shed", "reason": shed.reason,
                   "retry_after_s": shed.retry_after_s}
        headers = {"Retry-After": str(retry_after)}
        accept = request.headers.get("Accept", "")
        if "text/event-stream" in accept:
            body = (b"event: error\ndata: " + json.dumps(payload).encode()
                    + b"\n\n")
            return web.Response(status=429, headers=headers, body=body,
                                content_type="text/event-stream")
        return web.json_response(payload, status=429, headers=headers)

    async def _stream_response(self, request, stream, start, retry=None):
        """Generator-returning deployment over HTTP: chunked SSE — each
        produced item is one ``data:`` event, flushed as it arrives, so
        token streams reach the client incrementally instead of buffering
        to completion (reference: serve's StreamingResponse proxying).

        Replica-death failover: a stream is replica-affine, so losing the
        replica BEFORE the first chunk reached the client is invisible to
        them — re-issue the call once on another replica (``retry``).
        After the first chunk the output is already partially consumed and
        a silent re-run would duplicate it: emit a terminal ``event:
        error`` SSE frame instead of hanging or replaying."""
        from aiohttp import web

        from ray_tpu.exceptions import RayActorError
        from ray_tpu.serve._streaming import ResponseStream

        loop = asyncio.get_event_loop()
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        })
        await resp.prepare(request)
        status = "200"
        wrote_chunk = False
        retried = False
        try:
            while True:
                # each pull blocks on the replica long-poll: executor thread
                try:
                    items, done = await loop.run_in_executor(
                        None, stream.next_batch, 30.0)
                except RayActorError:
                    if wrote_chunk or retried or retry is None:
                        raise  # -> terminal error event below
                    retried = True
                    from ray_tpu._private import incidents

                    inc = incidents.open_incident(
                        "serve", kind="replica_failover",
                        detail=request.path)
                    inc.stamp("detect")
                    out = await loop.run_in_executor(None, retry)
                    if not isinstance(out, ResponseStream):
                        inc.close(ok=False)
                        raise  # app no longer streams: can't splice it in
                    stream = out
                    # re-issued on a fresh replica: stream restored
                    inc.stamp("restore")
                    inc.close()
                    continue
                for item in items:
                    if isinstance(item, bytes):
                        payload = item
                    elif isinstance(item, str):
                        payload = item.encode()
                    else:
                        payload = json.dumps(item).encode()
                    await resp.write(b"data: " + payload + b"\n\n")
                    wrote_chunk = True
                if done:
                    await resp.write(b"data: [DONE]\n\n")
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: stop the replica-side generator
            status = "499"
            await loop.run_in_executor(None, stream.cancel)
            raise
        except Exception as e:
            status = "500"
            try:
                await resp.write(
                    b"event: error\ndata: " +
                    f"{type(e).__name__}: {e}".encode() + b"\n\n")
            except Exception:
                pass
        finally:
            self._observe_ingress("http", status, start)
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp

    def _ensure_routes_listener(self):
        import threading

        if self._routes_listener is not None \
                and self._routes_listener.is_alive():
            return
        self._routes_listener = threading.Thread(
            target=self._routes_listen_loop, daemon=True,
            name="serve-proxy-routes")
        self._routes_listener.start()

    def _routes_listen_loop(self):
        import time as _time

        from ray_tpu.serve._controller import get_controller

        version = 0
        while True:
            try:
                out = ray_tpu.get(get_controller().listen_for_change.remote(
                    {"routes": version}, 30.0), timeout=45)
            except Exception:
                _time.sleep(1.0)
                continue
            entry = (out or {}).get("routes")
            if entry:
                version = entry["version"]
                self._routes = dict(entry["value"])

    def _route_and_call(self, path: str, body):
        from ray_tpu.serve._controller import get_controller

        self._ensure_routes_listener()
        routes = self._routes
        if routes is None:  # bootstrap before the first push lands
            ctrl = get_controller()
            routes = ray_tpu.get(ctrl.get_routes.remote(), timeout=30)
            self._routes = routes
        # longest matching prefix wins (reference: proxy route resolution)
        def match(routes):
            best = None
            for prefix, app_name in routes.items():
                if path == prefix \
                        or path.startswith(prefix.rstrip("/") + "/") \
                        or prefix == "/":
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, app_name)
            return best

        best = match(routes)
        if best is None:
            # a request can race the deploy's push: confirm the miss
            # against the controller before 404ing
            routes = ray_tpu.get(
                get_controller().get_routes.remote(), timeout=30)
            self._routes = routes
            best = match(routes)
        if best is None:
            raise LookupError(path)
        return self._call_app(best[1], body)

    def _call_app(self, app_name: str, body):
        """Shared HTTP/gRPC fan-in: one handle (one pow-2 router) per
        (app, ingress) regardless of which ingress the request used."""
        from ray_tpu.serve._controller import get_controller
        from ray_tpu.serve.handle import DeploymentHandle

        ctrl = get_controller()
        # keyed by (app, ingress): a redeploy can change the ingress
        # deployment, and a handle cached on app name alone would route 500s
        ingress = ray_tpu.get(ctrl.get_ingress.remote(app_name), timeout=30)
        if ingress is None:
            raise LookupError(app_name)
        key = (app_name, ingress)
        handle = self._handles.get(key)
        if handle is None:
            handle = DeploymentHandle(app_name, ingress)
            self._handles[key] = handle
        return handle.remote(body).result(60.0)
