"""Replica actor: hosts one instance of a deployment's user class.

Reference: python/ray/serve/_private/replica.py — the replica wraps the user
callable, enforces max_ongoing_requests, exposes health checks and stats.
TPU note: a replica is the natural unit that owns a chip (or a mesh slice);
the user class jit-compiles once in __init__ and every request hits the
compiled function, so the request path stays out of Python-compile land.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional

import ray_tpu


@ray_tpu.remote
class ServeReplica:
    def __init__(self, serialized_cls: bytes, init_args, init_kwargs,
                 max_ongoing_requests: int, app_name: str = "",
                 deployment_name: str = ""):
        import cloudpickle

        cls = cloudpickle.loads(serialized_cls)
        self._user = cls(*init_args, **(init_kwargs or {}))
        self._max_ongoing = max_ongoing_requests
        self._app = app_name
        self._deployment = deployment_name
        self._ongoing = 0
        self._total = 0
        self._started_at = time.time()
        # library metrics: per-deployment request counter/latency/queue
        # depth, pushed to the nodelet by this worker's CoreWorker loop
        from ray_tpu.serve._metrics import serve_metrics

        self._metrics = serve_metrics()
        self._metric_labels = {"app": app_name, "deployment": deployment_name}
        # multiplex: loader caches report loaded-model sets through this
        # hook; fire-and-forget to the controller, fanned to routers via
        # long-poll (reference: replica multiplexed_model_ids reporting)
        from ray_tpu.serve import multiplex as _mux

        self._mux = _mux
        self._mux_seq = 0
        self._mux_seq_lock = __import__("threading").Lock()
        _mux._set_report_hook(self._report_models)
        # in-flight response streams (generator-returning callables):
        # stream_id -> _StreamState, IO-loop confined
        self._streams: Dict[str, Any] = {}

    def _report_models(self, model_ids):
        # Runs on the replica's IO loop (model-cache finally): the controller
        # LOOKUP is a blocking runtime call and would wedge the loop (pings
        # stop dispatching, health checks kill the replica) — do the whole
        # report on a thread.  Each report carries a sequence number: the
        # threads' fire-and-forget sends can arrive out of order, and a
        # stale earlier snapshot must not overwrite a newer one.
        import threading

        with self._mux_seq_lock:
            self._mux_seq += 1
            seq = self._mux_seq

        def do():
            try:
                from ray_tpu.serve._controller import get_controller

                rid = ray_tpu.get_runtime_context().get_actor_id()
                get_controller().record_multiplexed_models.remote(
                    self._app, self._deployment, rid, model_ids, seq)
            except Exception:
                pass

        threading.Thread(target=do, daemon=True,
                         name="serve-mux-report").start()

    async def handle_request(self, method: str, args, kwargs,
                             multiplexed_model_id: str = "") -> Any:
        """Run one request through the user callable.  The handle-level router
        already respects max_ongoing_requests; the replica enforces it again
        as a backstop (reference: replica backpressure).

        Sync user code runs on an executor thread: this method itself runs on
        the worker's IO loop, and user code may make blocking runtime calls
        (composition: handle.remote().result()) that must not block the loop.
        Async user code (incl. @serve.batch wrappers) stays on the loop."""
        while self._ongoing >= self._max_ongoing:
            await asyncio.sleep(0.005)
        self._ongoing += 1
        self._total += 1
        m, labels = self._metrics, self._metric_labels
        m["queue_depth"].set(self._ongoing, labels)
        start = time.perf_counter()
        failed = False
        token = self._mux._model_id_ctx.set(multiplexed_model_id)
        try:
            call = getattr(self._user, method, None)
            if call is None:
                raise AttributeError(f"deployment has no method {method!r}")
            kwargs = kwargs or {}
            args, kwargs = await self._resolve_refs(args, kwargs)
            if inspect.iscoroutinefunction(call):
                out = call(*args, **kwargs)
            else:
                loop = asyncio.get_event_loop()
                ctx = __import__("contextvars").copy_context()
                out = await loop.run_in_executor(
                    None, lambda: ctx.run(call, *args, **kwargs))
            if inspect.isawaitable(out):
                out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                # streaming result: park the generator here, hand the
                # caller a pull handle (see serve/_streaming.py)
                out = self._start_stream(out)
                m["streams"].inc(1, labels)
            return out
        except BaseException:
            failed = True
            raise
        finally:
            self._mux._model_id_ctx.reset(token)
            self._ongoing -= 1
            m["queue_depth"].set(self._ongoing, labels)
            m["requests"].inc(1, labels)
            if failed:
                m["request_errors"].inc(1, labels)
            m["latency"].observe(time.perf_counter() - start, labels)

    async def _resolve_refs(self, args, kwargs):
        """Resolve top-level ObjectRefs (chained DeploymentResponses) to
        values, mirroring actor-call argument semantics (reference: handles
        pass the upstream ref; the downstream replica awaits it)."""
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private.worker import get_async

        args = list(args)
        for i, a in enumerate(args):
            if isinstance(a, ObjectRef):
                args[i] = await get_async(a)
        kwargs = dict(kwargs)
        for k, v in list(kwargs.items()):
            if isinstance(v, ObjectRef):
                kwargs[k] = await get_async(v)
        return tuple(args), kwargs

    # ------------------------------------------------------- streaming
    def _start_stream(self, gen):
        """Register a generator result as a pullable stream; returns the
        StreamHeader the caller unwraps into a ResponseStream."""
        import uuid

        from ray_tpu.serve._streaming import (
            STREAM_TTL_S,
            StreamHeader,
            _StreamState,
        )

        # lazy sweep: done-but-never-drained streams must not accumulate
        now = time.monotonic()
        for sid, st in list(self._streams.items()):
            if st.done and now - st.created > STREAM_TTL_S:
                del self._streams[sid]

        sid = uuid.uuid4().hex[:16]
        st = _StreamState()
        st.producer_ev = asyncio.Event()
        self._streams[sid] = st
        st.producer = asyncio.get_event_loop().create_task(
            self._pump_stream(sid, st, gen))
        return StreamHeader(sid)

    async def _pump_stream(self, sid, st, gen):
        """Drain the generator into the stream buffer.  Sync generators are
        pulled item-by-item on executor threads (their body may block on
        runtime calls); async generators run on the loop."""
        from ray_tpu.serve._streaming import MAX_BUFFERED_ITEMS

        import inspect as _inspect

        _SENTINEL = object()
        loop = asyncio.get_event_loop()
        try:
            if _inspect.isasyncgen(gen):
                async for item in gen:
                    await self._stream_put(st, item, MAX_BUFFERED_ITEMS)
            else:
                while True:
                    item = await loop.run_in_executor(
                        None, next, gen, _SENTINEL)
                    if item is _SENTINEL:
                        break
                    await self._stream_put(st, item, MAX_BUFFERED_ITEMS)
        except asyncio.CancelledError:
            st.error = "stream cancelled"
            raise
        except BaseException as e:
            st.error = f"{type(e).__name__}: {e}"
        finally:
            st.done = True
            st.wake()
            # cancelled/abandoned streams: the entry survives until drained
            # or swept; st.created reset so TTL counts from completion
            st.created = time.monotonic()

    async def _stream_put(self, st, item, cap):
        while len(st.items) - st.consumed >= cap:
            # backpressure: wait for a consumer to advance
            st.producer_ev.clear()
            await st.producer_ev.wait()
        st.items.append(item)
        st.wake()

    async def stream_next(self, stream_id: str, cursor: int,
                          timeout_s: float = 30.0) -> Dict[str, Any]:
        """Long-poll: items past ``cursor`` (or done/error state).  Fully
        drained done streams are dropped from the table."""
        st = self._streams.get(stream_id)
        if st is None:
            raise KeyError(f"unknown or expired stream {stream_id!r}")
        deadline = time.monotonic() + timeout_s
        while len(st.items) <= cursor and not st.done:
            ev = asyncio.Event()
            st.waiters.append(ev)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"items": [], "done": False, "error": None}
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return {"items": [], "done": False, "error": None}
        items = st.items[cursor:]
        new_cursor = cursor + len(items)
        if new_cursor > st.consumed:
            st.consumed = new_cursor
            if st.producer_ev is not None:
                st.producer_ev.set()
        done = st.done and new_cursor >= len(st.items)
        if done:
            self._streams.pop(stream_id, None)
        return {"items": items, "done": done, "error": st.error}

    async def stream_cancel(self, stream_id: str) -> bool:
        st = self._streams.pop(stream_id, None)
        if st is None:
            return False
        if st.producer is not None and not st.producer.done():
            st.producer.cancel()
        st.done = True
        st.wake()
        return True

    def stats(self) -> Dict[str, Any]:
        ongoing = self._ongoing
        # deployments that queue work behind the request path (e.g. an LLM
        # engine's admission queue) surface it through this protocol hook so
        # the controller's queue-depth autoscaler sees the real backlog
        extra = getattr(self._user, "__serve_queue_len__", None)
        if extra is not None:
            try:
                ongoing += int(extra())
            except Exception:
                pass
        return {"ongoing": ongoing, "total": self._total,
                "streams": len(self._streams),
                "uptime_s": time.time() - self._started_at}

    def ping(self) -> bool:
        check = getattr(self._user, "check_health", None)
        if check is not None:
            check()
        return True

    async def drain(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while self._ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        return self._ongoing == 0
