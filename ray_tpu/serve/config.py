"""Serve configuration dataclasses.

Reference: python/ray/serve/config.py (DeploymentConfig, AutoscalingConfig —
pydantic there; plain dataclasses here, validated in __post_init__).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth-driven replica autoscaling (reference:
    serve/autoscaling_policy.py — replicas sized so each carries about
    ``target_ongoing_requests`` in-flight calls)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0

    def __post_init__(self):
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_ongoing_requests <= 0:
            raise ValueError("max_ongoing_requests must be > 0")
        if isinstance(self.autoscaling_config, dict):
            self.autoscaling_config = AutoscalingConfig(**self.autoscaling_config)
