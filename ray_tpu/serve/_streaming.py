"""Serve response streaming: replica-held generators, client-side pulls.

Reference: Ray Serve's streaming responses (generator deployments +
StreamingResponse over the replica's generator protocol), condensed to this
runtime's primitives: when a deployment callable returns a (sync or async)
generator, the replica drains it into a per-stream buffer and returns a
small picklable ``StreamHeader``; the caller's DeploymentResponse unwraps
that into a ``ResponseStream`` that long-polls ``replica.stream_next`` for
incremental chunks.  The HTTP proxy turns a ResponseStream into a chunked
SSE response, so engine token streams reach HTTP clients token by token
instead of buffering to completion.

Flow control: the replica parks the producing generator once
``MAX_BUFFERED_ITEMS`` results sit unconsumed, so a slow client bounds the
replica-side buffer instead of growing it without limit.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

# replica-side cap on produced-but-unconsumed items per stream
MAX_BUFFERED_ITEMS = 4096
# done streams that were never fully drained are dropped after this long
STREAM_TTL_S = 600.0


class StreamHeader:
    """Picklable marker a replica returns in place of a generator result."""

    __slots__ = ("stream_id",)

    def __init__(self, stream_id: str):
        self.stream_id = stream_id

    def __reduce__(self):
        return (StreamHeader, (self.stream_id,))

    def __repr__(self):
        return f"StreamHeader({self.stream_id})"


class ResponseStream:
    """Client-side iterator over a replica-held stream.  Synchronous
    (blocking pulls) — consume from a thread or iterate directly; every
    pull fetches ALL items produced since the last one, so a fast producer
    costs O(items/batch) round trips, not O(items)."""

    def __init__(self, replica, stream_id: str):
        self._replica = replica
        self.stream_id = stream_id
        self._cursor = 0
        self._done = False

    def next_batch(self, timeout_s: float = 30.0
                   ) -> Tuple[List[Any], bool]:
        """(items_since_last_call, stream_done).  Empty list + False means
        the poll timed out with the stream still open."""
        import ray_tpu

        if self._done:
            return [], True
        ref = self._replica.stream_next.remote(
            self.stream_id, self._cursor, timeout_s)
        out = ray_tpu.get(ref, timeout=timeout_s + 30.0)
        items = out["items"]
        self._cursor += len(items)
        self._done = out["done"]
        if out.get("error") and self._done:
            raise RuntimeError(f"stream failed mid-generation: "
                               f"{out['error']}")
        return items, self._done

    def __iter__(self):
        while True:
            items, done = self.next_batch()
            for item in items:
                yield item
            if done:
                return

    def cancel(self) -> None:
        """Drop the replica-side stream (stops the producing generator at
        its next yield)."""
        import ray_tpu

        try:
            ray_tpu.get(self._replica.stream_cancel.remote(self.stream_id),
                        timeout=10)
        except Exception:
            pass
        self._done = True


class _StreamState:
    """Replica-side buffer for one in-flight stream (IO-loop confined)."""

    __slots__ = ("items", "done", "error", "created", "waiters", "producer",
                 "consumed", "producer_ev")

    def __init__(self):
        self.items: List[Any] = []
        self.done = False
        self.error: Optional[str] = None
        self.created = time.monotonic()
        self.waiters: List[Any] = []  # asyncio.Event per parked consumer
        self.producer = None          # asyncio.Task draining the generator
        self.consumed = 0             # highest cursor a consumer has read to
        self.producer_ev = None       # producer's backpressure event

    def wake(self) -> None:
        for ev in self.waiters:
            ev.set()
        self.waiters.clear()
