"""ServeController: the Serve control plane, one detached actor.

Reference: python/ray/serve/_private/controller.py:86 (ServeController),
application_state.py / deployment_state.py (state machines),
autoscaling_state.py (queue-metric autoscaling).  Same shape, condensed: the
controller holds the declarative app spec, and a reconcile loop drives the
actual replica actors toward it — creating, replacing dead ones, and scaling
counts from replica-reported ongoing-request stats.

Threading note: this is a SYNC actor — its methods run on executor threads
where blocking runtime calls (actor creation, get, kill) are legal; the
reconcile loop is a daemon thread for the same reason.  An async design would
deadlock: async actor methods run on the worker's IO loop, and actor creation
blocks on that loop.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._replica import ServeReplica
from ray_tpu.serve.config import DeploymentConfig

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
_RECONCILE_PERIOD_S = 0.25


class _DeploymentState:
    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec            # serialized_cls, init_args/kwargs, config
        self.config: DeploymentConfig = spec["config"]
        self.replicas: List[Any] = []
        self.target = (self.config.autoscaling_config.min_replicas
                       if self.config.autoscaling_config
                       else self.config.num_replicas)
        self.scale_signal_since: Optional[float] = None
        self.last_health_check = 0.0


@ray_tpu.remote(num_cpus=0)
class ServeController:
    def __init__(self):
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._ingress: Dict[str, str] = {}       # app -> ingress deployment
        self._routes: Dict[str, str] = {}        # route_prefix -> app
        self._proxy = None
        self._proxy_port: Optional[int] = None
        self._shutting_down = False
        self._lock = threading.RLock()
        # Serializes whole reconcile passes: deploy/delete call _reconcile_once
        # from the controller executor thread while the daemon loop runs its
        # own — concurrent passes would double-provision the same deficit.
        self._reconcile_mutex = threading.Lock()
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True,
            name="serve-controller-reconcile")
        self._thread.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(self, name: str, deployments: List[dict],
                           ingress: str, route_prefix: Optional[str]):
        """Declare (or redeclare) an app; reconcile makes it real."""
        to_stop = []
        with self._lock:
            new = {}
            old = self._apps.get(name, {})
            for spec in deployments:
                d = _DeploymentState(spec["name"], spec)
                prev = old.pop(spec["name"], None)
                if prev is not None and prev.spec["version"] == spec["version"]:
                    d.replicas = prev.replicas      # unchanged: keep replicas
                    d.target = prev.target
                elif prev is not None:
                    to_stop.append(prev)            # code/config changed
                new[spec["name"]] = d
            to_stop.extend(old.values())            # removed from the app
            self._apps[name] = new
            self._ingress[name] = ingress
            if route_prefix is not None:
                self._routes = {p: a for p, a in self._routes.items()
                                if a != name}
                self._routes[route_prefix] = name
        for d in to_stop:
            self._stop_replicas(d)
        self._reconcile_once()
        return True

    def delete_application(self, name: str):
        with self._lock:
            app = self._apps.pop(name, None)
            self._ingress.pop(name, None)
            self._routes = {p: a for p, a in self._routes.items() if a != name}
        if app:
            for d in app.values():
                self._stop_replicas(d)
        return True

    def shutdown(self):
        self._shutting_down = True
        for name in list(self._apps):
            self.delete_application(name)
        if self._proxy is not None:
            try:
                ray_tpu.kill(self._proxy)
            except Exception:
                pass
            self._proxy = None
        return True

    # ------------------------------------------------------------- queries
    def get_replicas(self, app: str, deployment: str) -> List[Any]:
        with self._lock:
            d = self._apps.get(app, {}).get(deployment)
            return list(d.replicas) if d else []

    def get_ingress(self, app: str) -> Optional[str]:
        return self._ingress.get(app)

    def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                app: {name: {"target": d.target, "running": len(d.replicas)}
                      for name, d in deps.items()}
                for app, deps in self._apps.items()
            }

    def ensure_proxy(self, host: str, port: int,
                     grpc_port=None) -> int:
        if self._proxy is None:
            from ray_tpu.serve._proxy import ProxyActor

            self._proxy = ProxyActor.options(num_cpus=0).remote(
                host, port, grpc_port)
            self._proxy_port = ray_tpu.get(self._proxy.ready.remote(),
                                           timeout=60)
        elif grpc_port is not None:
            # proxy already up without gRPC: upgrade it in place rather than
            # silently ignoring the documented parameter
            ray_tpu.get(self._proxy.enable_grpc.remote(grpc_port), timeout=60)
        return self._proxy_port

    def proxy_grpc_port(self):
        if self._proxy is None:
            return None
        return ray_tpu.get(self._proxy.grpc_port.remote(), timeout=30)

    # ---------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        while not self._shutting_down:
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("serve reconcile iteration failed")
            time.sleep(_RECONCILE_PERIOD_S)

    def _reconcile_once(self):
        with self._reconcile_mutex:
            with self._lock:
                work = [(app, d) for app, deps in self._apps.items()
                        for d in deps.values()]
            for app, d in work:
                self._health_check(d)
                self._autoscale(d)
                with self._lock:
                    missing = d.target - len(d.replicas)
                    surplus = [d.replicas.pop() for _ in
                               range(len(d.replicas) - d.target)] \
                        if len(d.replicas) > d.target else []
                for _ in range(max(missing, 0)):
                    r = self._start_replica(app, d)
                    with self._lock:
                        # A redeploy may have swapped this state out while we
                        # were creating: don't leak the replica onto a
                        # discarded _DeploymentState.
                        if self._apps.get(app, {}).get(d.name) is d:
                            d.replicas.append(r)
                        else:
                            surplus.append(r)
                for victim in surplus:
                    self._stop_one(victim)

    def _start_replica(self, app: str, d: _DeploymentState):
        opts = dict(d.config.ray_actor_options or {})
        opts.setdefault("num_cpus", 0)
        return ServeReplica.options(**opts).remote(
            d.spec["serialized_cls"], d.spec["init_args"],
            d.spec["init_kwargs"], d.config.max_ongoing_requests)

    def _health_check(self, d: _DeploymentState):
        now = time.monotonic()
        if now - d.last_health_check < d.config.health_check_period_s:
            return
        d.last_health_check = now
        with self._lock:
            replicas = list(d.replicas)
        dead = []
        for r in replicas:
            try:
                ray_tpu.get(r.ping.remote(),
                            timeout=d.config.health_check_timeout_s)
            except Exception:
                logger.warning("serve replica failed health check; replacing")
                dead.append(r)
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        if dead:
            with self._lock:
                d.replicas = [r for r in d.replicas if r not in dead]

    def _autoscale(self, d: _DeploymentState):
        cfg = d.config.autoscaling_config
        if cfg is None or not d.replicas:
            return
        total_ongoing = 0
        for r in list(d.replicas):
            try:
                st = ray_tpu.get(r.stats.remote(), timeout=5)
                total_ongoing += st["ongoing"]
            except Exception:
                pass
        desired = max(
            cfg.min_replicas,
            min(cfg.max_replicas,
                round(total_ongoing / cfg.target_ongoing_requests) or
                cfg.min_replicas))
        now = time.monotonic()
        if desired == d.target:
            d.scale_signal_since = None
            return
        delay = (cfg.upscale_delay_s if desired > d.target
                 else cfg.downscale_delay_s)
        if d.scale_signal_since is None:
            d.scale_signal_since = now
        if now - d.scale_signal_since >= delay:
            logger.info("autoscaling %s: %d -> %d (ongoing=%d)",
                        d.name, d.target, desired, total_ongoing)
            d.target = desired
            d.scale_signal_since = None

    def _stop_one(self, replica):
        """Graceful stop: let in-flight requests finish, then kill (reference:
        replica draining on scale-down)."""
        try:
            ray_tpu.get(replica.drain.remote(timeout_s=5.0), timeout=10)
        except Exception:
            pass
        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _stop_replicas(self, d: _DeploymentState):
        with self._lock:
            replicas, d.replicas = list(d.replicas), []
        for r in replicas:
            self._stop_one(r)


def get_controller(create: bool = False):
    """Look up (or start) the singleton controller actor."""
    from ray_tpu.actor import get_actor

    try:
        return get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise RuntimeError(
                "Serve is not running on this cluster (serve.run first)")
    return ServeController.options(
        name=CONTROLLER_NAME, lifetime="detached").remote()
