"""ServeController: the Serve control plane, one detached actor.

Reference: python/ray/serve/_private/controller.py:86 (ServeController),
application_state.py / deployment_state.py (state machines),
autoscaling_state.py (queue-metric autoscaling).  Same shape, condensed: the
controller holds the declarative app spec, and a reconcile loop drives the
actual replica actors toward it — creating, replacing dead ones, and scaling
counts from replica-reported ongoing-request stats.

Threading note: sync methods run on executor threads where blocking runtime
calls (actor creation, get, kill) are legal; the reconcile loop is a daemon
thread for the same reason.  ``listen_for_change`` is the ONE async method
(parked listeners must cost an event, not a thread) — its presence makes
this a high-concurrency actor, so sync methods can now run CONCURRENTLY on
executor threads and every mutation must hold a lock.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._replica import ServeReplica
from ray_tpu.serve.config import DeploymentConfig

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
_RECONCILE_PERIOD_S = 0.25


class _DeploymentState:
    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec            # serialized_cls, init_args/kwargs, config
        self.config: DeploymentConfig = spec["config"]
        self.replicas: List[Any] = []
        self.target = (self.config.autoscaling_config.min_replicas
                       if self.config.autoscaling_config
                       else self.config.num_replicas)
        self.scale_signal_since: Optional[float] = None
        self.last_health_check = 0.0


class _LongPollHost:
    """Versioned-key push channel (reference:
    serve/_private/long_poll.py LongPollHost:93 — listen_for_change blocks
    until any watched key moves past the client's snapshot version).

    Publishers run on controller executor/reconcile THREADS; listeners park
    on the worker's IO loop (async actor method), so wakeups cross via
    ``loop.call_soon_threadsafe``.
    """

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._waiters: Dict[int, tuple] = {}  # id -> (loop, event, keys)
        self._next_waiter = 0
        self._lock = threading.Lock()

    def publish(self, key: str, value: Any) -> None:
        """Bump + wake only on actual change (idempotent republish from the
        reconcile loop must not spin listeners)."""
        import asyncio  # noqa: F401  (documenting the loop dependency)

        with self._lock:
            if key in self._versions and self._values.get(key) == value:
                return
            self._values[key] = value
            self._versions[key] = self._versions.get(key, 0) + 1
            wake = [(loop, ev) for loop, ev, keys in self._waiters.values()
                    if key in keys]
        for loop, ev in wake:
            loop.call_soon_threadsafe(ev.set)

    async def listen(self, snapshot: Dict[str, int], timeout_s: float):
        """Return {key: {"version": v, "value": ...}} for every watched key
        newer than the client's snapshot; block (on the IO loop) until one
        changes or the timeout passes ({} -> client re-issues)."""
        import asyncio

        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                out = {k: {"version": self._versions[k],
                           "value": self._values[k]}
                       for k in snapshot
                       if self._versions.get(k, 0) > snapshot[k]}
                if out:
                    return out
                loop = asyncio.get_event_loop()
                ev = asyncio.Event()
                wid = self._next_waiter
                self._next_waiter += 1
                self._waiters[wid] = (loop, ev, set(snapshot))
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    return {}
            finally:
                with self._lock:
                    self._waiters.pop(wid, None)


@ray_tpu.remote(num_cpus=0)
class ServeController:
    def __init__(self):
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._ingress: Dict[str, str] = {}       # app -> ingress deployment
        self._routes: Dict[str, str] = {}        # route_prefix -> app
        self._proxy = None
        self._proxy_port: Optional[int] = None
        self._shutting_down = False
        self._lock = threading.RLock()
        self._lp = _LongPollHost()
        # concurrent serve.start()/run() calls must not double-bind a proxy
        self._proxy_lock = threading.Lock()
        # (app, deployment) -> {replica_actor_id_hex: [model ids]}
        self._multiplex: Dict[tuple, Dict[str, list]] = {}
        from collections import deque

        from ray_tpu.serve._metrics import serve_metrics

        self._metrics = serve_metrics()
        # bounded autoscaler decision log, queryable via
        # get_autoscaler_events (surfaced by state.summarize_serve / the
        # `ray_tpu summary serve` CLI)
        self._autoscale_events: deque = deque(maxlen=256)
        # Serializes whole reconcile passes: deploy/delete call _reconcile_once
        # from the controller executor thread while the daemon loop runs its
        # own — concurrent passes would double-provision the same deficit.
        self._reconcile_mutex = threading.Lock()
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True,
            name="serve-controller-reconcile")
        self._thread.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(self, name: str, deployments: List[dict],
                           ingress: str, route_prefix: Optional[str]):
        """Declare (or redeclare) an app; reconcile makes it real."""
        to_stop = []
        with self._lock:
            new = {}
            old = self._apps.get(name, {})
            for spec in deployments:
                d = _DeploymentState(spec["name"], spec)
                prev = old.pop(spec["name"], None)
                if prev is not None and prev.spec["version"] == spec["version"]:
                    d.replicas = prev.replicas      # unchanged: keep replicas
                    d.target = prev.target
                elif prev is not None:
                    to_stop.append(prev)            # code/config changed
                new[spec["name"]] = d
            to_stop.extend(old.values())            # removed from the app
            self._apps[name] = new
            self._ingress[name] = ingress
            if route_prefix is not None:
                self._routes = {p: a for p, a in self._routes.items()
                                if a != name}
                self._routes[route_prefix] = name
        for d in to_stop:
            self._stop_replicas(d)
        self._lp.publish("routes", self.get_routes())
        self._reconcile_once()
        return True

    def delete_application(self, name: str):
        with self._lock:
            app = self._apps.pop(name, None)
            self._ingress.pop(name, None)
            self._routes = {p: a for p, a in self._routes.items() if a != name}
        if app:
            for d in app.values():
                self._stop_replicas(d)
                self._lp.publish(f"replicas::{name}/{d.name}", [])
                labels = {"app": name, "deployment": d.name}
                self._metrics["replicas"].set(0, labels)
                self._metrics["target_replicas"].set(0, labels)
        self._lp.publish("routes", self.get_routes())
        return True

    def shutdown(self):
        self._shutting_down = True
        for name in list(self._apps):
            self.delete_application(name)
        if self._proxy is not None:
            try:
                ray_tpu.kill(self._proxy)
            except Exception:
                pass
            self._proxy = None
        return True

    # ----------------------------------------------------------- long poll
    async def listen_for_change(self, snapshot: Dict[str, int],
                                timeout_s: float = 30.0):
        """Push channel for routers/handles (reference: long_poll.py
        LongPollHost.listen_for_change).  Runs as an ASYNC actor method so a
        parked listener costs an event, not an executor thread."""
        return await self._lp.listen(snapshot, timeout_s)

    def record_multiplexed_models(self, app: str, deployment: str,
                                  replica_id: str, model_ids: List[str],
                                  seq: int = 0):
        """Replica -> controller report of its loaded model set; fanned out
        to routers via long-poll (reference: serve/multiplex.py model
        registry + RunningReplicaInfo.multiplexed_model_ids).  ``seq`` is
        the replica's report counter — reports ride independent
        fire-and-forget sends, so an out-of-order stale snapshot must lose
        to the newer one already applied."""
        key = (app, deployment)
        with self._lock:
            m = self._multiplex.setdefault(key, {})
            prev_seq, _ = m.get(replica_id, (0, None))
            if seq and seq <= prev_seq:
                return True
            m[replica_id] = (seq, list(model_ids))
            value = {rid: list(models) for rid, (s_, models) in m.items()}
        self._lp.publish(f"multiplex::{app}/{deployment}", value)
        return True

    # ------------------------------------------------------------- queries
    def get_replicas(self, app: str, deployment: str) -> List[Any]:
        with self._lock:
            d = self._apps.get(app, {}).get(deployment)
            return list(d.replicas) if d else []

    def get_ingress(self, app: str) -> Optional[str]:
        return self._ingress.get(app)

    def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                app: {name: {"target": d.target, "running": len(d.replicas)}
                      for name, d in deps.items()}
                for app, deps in self._apps.items()
            }

    def ensure_proxy(self, host: str, port: int,
                     grpc_port=None) -> int:
        with self._proxy_lock:
            return self._ensure_proxy_locked(host, port, grpc_port)

    def _ensure_proxy_locked(self, host, port, grpc_port) -> int:
        if self._proxy is None:
            from ray_tpu.serve._proxy import ProxyActor

            self._proxy = ProxyActor.options(num_cpus=0).remote(
                host, port, grpc_port)
            self._proxy_port = ray_tpu.get(self._proxy.ready.remote(),
                                           timeout=60)
        elif grpc_port is not None:
            # proxy already up without gRPC: upgrade it in place rather than
            # silently ignoring the documented parameter
            ray_tpu.get(self._proxy.enable_grpc.remote(grpc_port), timeout=60)
        return self._proxy_port

    def proxy_grpc_port(self):
        if self._proxy is None:
            return None
        return ray_tpu.get(self._proxy.grpc_port.remote(), timeout=30)

    # ---------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        while not self._shutting_down:
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("serve reconcile iteration failed")
            time.sleep(_RECONCILE_PERIOD_S)

    def _reconcile_once(self):
        with self._reconcile_mutex:
            with self._lock:
                work = [(app, d) for app, deps in self._apps.items()
                        for d in deps.values()]
            for app, d in work:
                self._health_check(d)
                self._autoscale(app, d)
                with self._lock:
                    missing = d.target - len(d.replicas)
                    surplus = [d.replicas.pop() for _ in
                               range(len(d.replicas) - d.target)] \
                        if len(d.replicas) > d.target else []
                for _ in range(max(missing, 0)):
                    r = self._start_replica(app, d)
                    with self._lock:
                        # A redeploy may have swapped this state out while we
                        # were creating: don't leak the replica onto a
                        # discarded _DeploymentState.
                        if self._apps.get(app, {}).get(d.name) is d:
                            d.replicas.append(r)
                        else:
                            surplus.append(r)
                for victim in surplus:
                    self._stop_one(victim)
                # push the (possibly) new replica set; publish() no-ops when
                # nothing changed, so the steady-state loop stays silent
                with self._lock:
                    live = list(d.replicas)
                    live_ids = {r._actor_id.hex() for r in live}
                    m = self._multiplex.get((app, d.name))
                    mux_value = None
                    if m:
                        stale = set(m) - live_ids
                        for rid in stale:
                            del m[rid]
                        if stale:
                            mux_value = {rid: list(models)
                                         for rid, (s_, models) in m.items()}
                if mux_value is not None:
                    self._lp.publish(f"multiplex::{app}/{d.name}", mux_value)
                self._lp.publish(f"replicas::{app}/{d.name}", live)
                labels = {"app": app, "deployment": d.name}
                self._metrics["replicas"].set(len(live), labels)
                self._metrics["target_replicas"].set(d.target, labels)

    def _start_replica(self, app: str, d: _DeploymentState):
        opts = dict(d.config.ray_actor_options or {})
        opts.setdefault("num_cpus", 0)
        return ServeReplica.options(**opts).remote(
            d.spec["serialized_cls"], d.spec["init_args"],
            d.spec["init_kwargs"], d.config.max_ongoing_requests,
            app_name=app, deployment_name=d.name)

    def _health_check(self, d: _DeploymentState):
        now = time.monotonic()
        if now - d.last_health_check < d.config.health_check_period_s:
            return
        d.last_health_check = now
        with self._lock:
            replicas = list(d.replicas)
        dead = []
        for r in replicas:
            try:
                ray_tpu.get(r.ping.remote(),
                            timeout=d.config.health_check_timeout_s)
            except Exception:
                logger.warning("serve replica failed health check; replacing")
                dead.append(r)
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        if dead:
            with self._lock:
                d.replicas = [r for r in d.replicas if r not in dead]

    def _autoscale(self, app: str, d: _DeploymentState):
        cfg = d.config.autoscaling_config
        if cfg is None or not d.replicas:
            return
        total_ongoing = 0
        for r in list(d.replicas):
            try:
                st = ray_tpu.get(r.stats.remote(), timeout=5)
                total_ongoing += st["ongoing"]
            except Exception:
                pass
        desired = max(
            cfg.min_replicas,
            min(cfg.max_replicas,
                round(total_ongoing / cfg.target_ongoing_requests) or
                cfg.min_replicas))
        now = time.monotonic()
        if desired == d.target:
            d.scale_signal_since = None
            return
        delay = (cfg.upscale_delay_s if desired > d.target
                 else cfg.downscale_delay_s)
        if d.scale_signal_since is None:
            d.scale_signal_since = now
        if now - d.scale_signal_since >= delay:
            logger.info("autoscaling %s: %d -> %d (ongoing=%d)",
                        d.name, d.target, desired, total_ongoing)
            direction = "up" if desired > d.target else "down"
            self._metrics["autoscale_decisions"].inc(
                1, {"app": app, "deployment": d.name,
                    "direction": direction})
            self._autoscale_events.append({
                "ts": time.time(), "app": app, "deployment": d.name,
                "from": d.target, "to": desired, "direction": direction,
                "ongoing": total_ongoing})
            d.target = desired
            d.scale_signal_since = None

    def get_autoscaler_events(self) -> List[dict]:
        """The bounded log of committed scale decisions, oldest first."""
        return list(self._autoscale_events)

    def _stop_one(self, replica):
        """Graceful stop: let in-flight requests finish, then kill (reference:
        replica draining on scale-down)."""
        try:
            ray_tpu.get(replica.drain.remote(timeout_s=5.0), timeout=10)
        except Exception:
            pass
        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _stop_replicas(self, d: _DeploymentState):
        with self._lock:
            replicas, d.replicas = list(d.replicas), []
        for r in replicas:
            self._stop_one(r)


def get_controller(create: bool = False):
    """Look up (or start) the singleton controller actor."""
    from ray_tpu.actor import get_actor

    try:
        return get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise RuntimeError(
                "Serve is not running on this cluster (serve.run first)")
    return ServeController.options(
        name=CONTROLLER_NAME, lifetime="detached").remote()
