"""Model multiplexing: many models per deployment, LRU-cached per replica.

Counterpart of the reference's multiplex surface (reference:
python/ray/serve/multiplex.py _ModelMultiplexWrapper — per-replica LRU of
loaded models with ``max_num_models_per_replica``; serve/api.py
get_multiplexed_model_id; router affinity to replicas already holding the
model via RunningReplicaInfo.multiplexed_model_ids).

This is the many-adapters-on-TPU serving pattern: one deployment hosts N
LoRA/finetune variants, each replica keeps a few resident in HBM, and the
router steers a request for model m to a replica that already loaded m —
cold loads happen only when no replica holds the model (or all holders are
overloaded), and the LRU evicts the coldest resident.

Mechanics: ``@serve.multiplexed`` wraps the user's model-loader method; the
replica runs requests with the target model id in a contextvar
(``serve.get_multiplexed_model_id()``), reports its loaded set to the
controller on every change, and the controller fans the map out to routers
over the long-poll channel.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
import inspect
import logging
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")
# set by ServeReplica (one replica per dedicated worker process, so a module
# global is correct — a contextvar set in __init__ would not survive into
# request contexts): called with the current list of loaded model ids
_report_hook = None


def _set_report_hook(hook) -> None:
    global _report_hook
    _report_hook = hook


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request targets (reference:
    serve/api.py get_multiplexed_model_id)."""
    return _model_id_ctx.get()


class _ModelCache:
    """Per-replica LRU of loaded models; loads are serialized per model id
    so concurrent requests for a cold model trigger ONE load."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._loads: dict = {}  # model_id -> asyncio.Future

    async def get(self, owner, model_id: str) -> Any:
        if model_id in self.models:
            self.models.move_to_end(model_id)
            return self.models[model_id]
        pending = self._loads.get(model_id)
        if pending is not None:
            return await pending
        fut = asyncio.get_event_loop().create_future()
        self._loads[model_id] = fut
        try:
            # evict BEFORE loading: at capacity, holding the residents
            # while the new weights stream in would transiently exceed the
            # HBM bound the cap exists to enforce (reference: multiplex
            # wrapper unloads before load)
            while len(self.models) >= self.max_models:
                old_id, old = self.models.popitem(last=False)
                logger.info("multiplex: evicting model %r", old_id)
                del old  # deleting the last ref releases weights/HBM
            if inspect.iscoroutinefunction(self.loader):
                out = await self.loader(owner, model_id)
            else:
                # sync loaders block (weight reads): executor thread, not
                # the replica's request loop
                out = await asyncio.get_event_loop().run_in_executor(
                    None, self.loader, owner, model_id)
            self.models[model_id] = out
            fut.set_result(out)
            return out
        except BaseException as e:
            fut.set_exception(e)
            # consume the exception if nobody else awaited the future
            fut.exception()
            raise
        finally:
            self._loads.pop(model_id, None)
            self._report()

    def _report(self):
        hook = _report_hook
        if hook is not None:
            try:
                hook(list(self.models))
            except Exception:
                logger.exception("multiplex report failed")


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for the deployment's model-loader method (reference:
    serve/multiplex.py @serve.multiplexed):

        @serve.deployment
        class Multi:
            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_model(self, model_id: str):
                return load_weights(model_id)

            async def __call__(self, x):
                model = await self.get_model(serve.get_multiplexed_model_id())
                return model(x)
    """
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(fn: Callable):
        caches: dict = {}

        @functools.wraps(fn)
        async def wrapper(self, model_id: Optional[str] = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            if not model_id:
                raise ValueError(
                    "no model id: pass one explicitly or send the request "
                    "with handle.options(multiplexed_model_id=...)")
            cache = caches.get(id(self))
            if cache is None:
                cache = caches[id(self)] = _ModelCache(
                    fn, max_num_models_per_replica)
            return await cache.get(self, model_id)

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if func is not None:
        return deco(func)
    return deco
