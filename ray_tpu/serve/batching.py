"""@serve.batch: transparent request batching inside a replica.

Reference: python/ray/serve/batching.py — calls queue up; when
max_batch_size accumulate or batch_wait_timeout_s elapses, the wrapped
function runs ONCE on the list of requests and each caller gets its element.

TPU note: this is the mechanism that turns single-request traffic into
MXU-shaped batches — a jitted model with a fixed batch dimension runs at a
fraction of peak on batch=1; the batcher amortizes compile shapes by padding
to max_batch_size where the user function chooses to.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._queue: List = []           # (arg, future)
        self._flush_task: Optional[asyncio.Task] = None

    async def submit(self, instance, arg) -> Any:
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._queue.append((arg, fut))
        if len(self._queue) >= self._max:
            self._flush(instance)
        elif self._flush_task is None:
            self._flush_task = loop.create_task(self._timer(instance))
        return await fut

    async def _timer(self, instance):
        await asyncio.sleep(self._wait)
        self._flush(instance)

    def _flush(self, instance):
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        batch, self._queue = self._queue, []
        if not batch:
            return
        args = [a for a, _ in batch]
        futs = [f for _, f in batch]
        asyncio.get_event_loop().create_task(
            self._run(instance, args, futs))

    async def _run(self, instance, args, futs):
        try:
            out = self._fn(instance, args) if instance is not None \
                else self._fn(args)
            if asyncio.iscoroutine(out):
                out = await out
            if len(out) != len(args):
                raise ValueError(
                    f"@serve.batch function returned {len(out)} results "
                    f"for {len(args)} requests")
            for f, o in zip(futs, out):
                _safe_resolve(f, result=o)
        except BaseException as e:
            for f in futs:
                _safe_resolve(f, exception=e)


def _safe_resolve(fut: asyncio.Future, result=None, exception=None) -> None:
    """Resolve one co-batched caller's future without letting a cancelled
    (or otherwise already-settled) future poison its batch-mates: an
    unguarded ``set_result`` raising InvalidStateError inside ``_run``'s
    result loop would divert every remaining future to the exception path,
    failing requests whose results are already in hand."""
    if fut.done():
        return
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
    except asyncio.InvalidStateError:
        pass  # cancelled between the check and the set


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for a replica method taking a LIST of requests."""

    def deco(fn):
        batcher_attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, arg):
            b = getattr(self, batcher_attr, None)
            if b is None:
                b = _Batcher(fn, max_batch_size, batch_wait_timeout_s)
                setattr(self, batcher_attr, b)
            return await b.submit(self, arg)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
