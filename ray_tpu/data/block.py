"""Columnar block model for ray_tpu.data.

A *block* is the unit of data the streaming executor moves between tasks.
TWO representations are first-class (reference: python/ray/data/block.py,
_internal/arrow_block.py — blocks are pyarrow Tables or pandas frames):

- dict[str, np.ndarray] — the TPU hand-off layout: round-trips the
  shared-memory store zero-copy via pickle-5 buffers and feeds
  ``jax.device_put`` without a pivot;
- ``pyarrow.Table`` — schema-carrying columnar format; parquet reads stay
  Arrow end-to-end through map_batches(batch_format="pyarrow") and
  iter_batches(batch_format="pyarrow") with no numpy round-trip (arrow
  buffers also pickle out-of-band, so plasma transport is zero-copy too);
- ``pandas.DataFrame`` — a pandas pipeline (``from_pandas`` source or a
  map_batches(batch_format="pandas") chain returning frames) flows
  frame-native with no per-stage pivot (reference:
  python/ray/data/_internal/pandas_block.py).

``BlockAccessor`` dispatches on the representation; all-to-all ops
(sort/shuffle/groupby) pivot to numpy at their barrier, where a row pivot
happens anyway.  Non-numeric python objects live in ``dtype=object``
columns, so arbitrary rows still fit the columnar frame.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], "pyarrow.Table", "pandas.DataFrame"]
Row = Dict[str, Any]


def is_arrow_block(block: Any) -> bool:
    if isinstance(block, dict):
        return False
    try:
        import pyarrow as pa

        return isinstance(block, pa.Table)
    except ImportError:
        return False


def is_pandas_block(block: Any) -> bool:
    if isinstance(block, dict):
        return False
    if "pandas" not in sys.modules:  # never import pandas just to say no
        return False
    import pandas as pd

    return isinstance(block, pd.DataFrame)


@dataclass
class BlockMetadata:
    """Sidecar stats the executor and Dataset.stats() read without fetching
    the block itself (reference: data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None
    input_files: List[str] = field(default_factory=list)


def _column(values: List[Any]) -> np.ndarray:
    """Build one column; fall back to object dtype for ragged/non-numeric."""
    try:
        arr = np.asarray(values)
        if arr.dtype.kind in "OUSV" and not all(
                isinstance(v, (str, bytes, np.str_, np.bytes_)) for v in values):
            raise ValueError
        # np.asarray silently collapses mixed-length sequences only on
        # dtype=object; anything else is a clean column.
        return arr
    except (ValueError, TypeError):
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr


class BlockAccessor:
    """Stateless helpers over the dict-of-numpy block format."""

    # ------------------------------------------------------------ construct
    @staticmethod
    def from_rows(rows: Sequence[Row]) -> Block:
        if not rows:
            return {}
        cols: Dict[str, List[Any]] = {}
        for i, r in enumerate(rows):
            if not isinstance(r, dict):
                r = {"item": r}
            for k in r:
                if k not in cols:
                    # column appearing late: backfill
                    cols[k] = [None] * i
            for k, vals in cols.items():
                vals.append(r.get(k) if isinstance(r, dict) else None)
        return {k: _column(v) for k, v in cols.items()}

    @staticmethod
    def from_pandas(df) -> Block:
        """DataFrames ARE a block representation: pass through unchanged so
        a pandas pipeline never pays a per-stage pivot."""
        return df

    @staticmethod
    def to_pandas(block: Block):
        import pandas as pd

        if is_pandas_block(block):
            return block
        if is_arrow_block(block):
            return block.to_pandas()
        return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                             for k, v in block.items()})

    @staticmethod
    def from_arrow(table) -> Dict[str, np.ndarray]:
        out = {}
        for name in table.column_names:
            col = table.column(name)
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except Exception:
                out[name] = _column(col.to_pylist())
        return out

    @staticmethod
    def to_numpy_block(block: Block) -> Dict[str, np.ndarray]:
        """Canonical numpy view (the jax hand-off / all-to-all pivot)."""
        if is_arrow_block(block):
            return BlockAccessor.from_arrow(block)
        if is_pandas_block(block):
            return {c: block[c].to_numpy() for c in block.columns}
        return block

    @staticmethod
    def to_arrow(block: Block):
        import pyarrow as pa

        if is_arrow_block(block):
            return block
        if is_pandas_block(block):
            return pa.Table.from_pandas(block, preserve_index=False)
        return pa.table({k: (list(v) if v.ndim > 1 or v.dtype.kind == "O"
                             else v)
                         for k, v in block.items()})

    # ------------------------------------------------------------ inspect
    @staticmethod
    def num_rows(block: Block) -> int:
        if is_arrow_block(block):
            return block.num_rows
        if is_pandas_block(block):
            return len(block)
        if not block:
            return 0
        return len(next(iter(block.values())))

    @staticmethod
    def size_bytes(block: Block) -> int:
        if is_arrow_block(block):
            return block.nbytes
        if is_pandas_block(block):
            # deep=True scans every object cell (O(n) strings); sample like
            # the numpy-dict path below — metadata runs on the read path
            total = 0
            for c in block.columns:
                col = block[c]
                if col.dtype == object:
                    n = len(col)
                    head = col.iloc[:100]
                    per = sum(64 + getattr(x, "nbytes", len(repr(x)))
                              for x in head)
                    total += per * max(1, n // max(1, min(n, 100)))
                else:
                    total += int(col.memory_usage(index=False, deep=False))
            return total
        total = 0
        for v in block.values():
            if v.dtype.kind == "O":
                # rough: object columns priced per-element via repr length
                total += sum(64 + getattr(x, "nbytes", len(repr(x)))
                             for x in v[:100]) * max(1, len(v) // max(1, min(len(v), 100)))
            else:
                total += v.nbytes
        return total

    @staticmethod
    def schema(block: Block) -> Dict[str, str]:
        if is_arrow_block(block):
            return {f.name: str(f.type) for f in block.schema}
        if is_pandas_block(block):
            return {c: str(block.dtypes[c]) for c in block.columns}
        out = {}
        for k, v in block.items():
            t = "object" if v.dtype.kind == "O" else str(v.dtype)
            if v.ndim > 1:
                t += str(list(v.shape[1:]))
            out[k] = t
        return out

    @staticmethod
    def metadata(block: Block,
                 input_files: Optional[List[str]] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=BlockAccessor.num_rows(block),
            size_bytes=BlockAccessor.size_bytes(block),
            schema=BlockAccessor.schema(block),
            input_files=input_files or [])

    # ------------------------------------------------------------ transform
    @staticmethod
    def slice(block: Block, start: int, end: int) -> Block:
        if is_arrow_block(block):
            return block.slice(start, max(end - start, 0))
        if is_pandas_block(block):
            # reset: a UDF assigning a fresh RangeIndex series to a batch
            # with index 5..9 would align-on-index into all-NaN
            return block.iloc[start:end].reset_index(drop=True)
        return {k: v[start:end] for k, v in block.items()}

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor.num_rows(b) > 0]
        if not blocks:
            return {}
        if len(blocks) == 1:
            return blocks[0]
        if all(is_pandas_block(b) for b in blocks):
            import pandas as pd

            first = set(blocks[0].columns)
            for i, b in enumerate(blocks[1:], 1):
                if set(b.columns) != first:
                    # pd.concat would silently outer-join with NaN fill;
                    # loud beats silent column loss (same rule as the dict
                    # and arrow paths)
                    raise ValueError(
                        f"cannot concat blocks with mismatched columns: "
                        f"{sorted(first)} vs {sorted(b.columns)} (block {i})")
            return pd.concat(list(blocks), ignore_index=True)
        if any(is_pandas_block(b) for b in blocks):
            blocks = [BlockAccessor.to_numpy_block(b)
                      if is_pandas_block(b) else b for b in blocks]
        if all(is_arrow_block(b) for b in blocks):
            import pyarrow as pa

            first = blocks[0].schema
            aligned = [blocks[0]]
            for i, b in enumerate(blocks[1:], 1):
                if b.schema != first:
                    # same columns in a different order is fine (multi-file
                    # reads don't guarantee order); anything else is a loud
                    # error (reference: arrow_block schema unification)
                    if set(b.schema.names) == set(first.names):
                        b = b.select(first.names)
                    if b.schema != first:
                        raise ValueError(
                            f"cannot concat Arrow blocks with mismatched "
                            f"schemas:\n{first}\nvs (block {i}):\n{b.schema}")
                aligned.append(b)
            return pa.concat_tables(aligned)
        if any(is_arrow_block(b) for b in blocks):
            blocks = [BlockAccessor.to_numpy_block(b) for b in blocks]
        keys = list(blocks[0].keys())
        for i, b in enumerate(blocks[1:], 1):
            if set(b.keys()) != set(keys):
                # loud beats silent column loss (reference: Arrow unification
                # errors on incompatible schemas)
                raise ValueError(
                    f"cannot concat blocks with mismatched columns: "
                    f"{sorted(keys)} vs {sorted(b.keys())} (block {i})")
        out = {}
        for k in keys:
            cols = [b[k] for b in blocks]
            if any(c.dtype.kind == "O" for c in cols):
                merged = np.empty(sum(len(c) for c in cols), dtype=object)
                i = 0
                for c in cols:
                    merged[i:i + len(c)] = c
                    i += len(c)
                out[k] = merged
            else:
                out[k] = np.concatenate(cols, axis=0)
        return out

    @staticmethod
    def iter_rows(block: Block) -> Iterator[Row]:
        if is_arrow_block(block):
            yield from block.to_pylist()
            return
        if is_pandas_block(block):
            cols = list(block.columns)
            for tup in block.itertuples(index=False, name=None):
                yield dict(zip(cols, tup))
            return
        keys = list(block.keys())
        for i in range(BlockAccessor.num_rows(block)):
            yield {k: block[k][i] for k in keys}

    @staticmethod
    def take_idx(block: Block, idx: np.ndarray) -> Block:
        if is_arrow_block(block):
            import pyarrow as pa

            return block.take(pa.array(np.asarray(idx)))
        if is_pandas_block(block):
            return block.iloc[np.asarray(idx)].reset_index(drop=True)
        return {k: v[idx] for k, v in block.items()}

    @staticmethod
    def select(block: Block, cols: Sequence[str]) -> Block:
        if is_arrow_block(block):
            missing = [c for c in cols if c not in block.column_names]
            if missing:
                raise KeyError(f"columns not in block: {missing}; "
                               f"available: {block.column_names}")
            return block.select(list(cols))
        if is_pandas_block(block):
            missing = [c for c in cols if c not in block.columns]
            if missing:
                raise KeyError(f"columns not in block: {missing}; "
                               f"available: {list(block.columns)}")
            return block[list(cols)]
        missing = [c for c in cols if c not in block]
        if missing:
            raise KeyError(f"columns not in block: {missing}; "
                           f"available: {list(block)}")
        return {c: block[c] for c in cols}

    @staticmethod
    def drop(block: Block, cols: Sequence[str]) -> Block:
        if is_arrow_block(block):
            return block.drop_columns(
                [c for c in cols if c in block.column_names])
        if is_pandas_block(block):
            return block.drop(columns=[c for c in cols
                                       if c in block.columns])
        return {k: v for k, v in block.items() if k not in cols}

    @staticmethod
    def sort_key_array(block: Block, key: str, descending: bool = False):
        if is_arrow_block(block):
            col = block.column(key).to_numpy(zero_copy_only=False)
        elif is_pandas_block(block):
            col = block[key].to_numpy()
        else:
            col = block[key]
        order = np.argsort(col, kind="stable")
        if descending:
            order = order[::-1]
        return order

    @staticmethod
    def normalize(batch: Any, what: str = "map_batches") -> Block:
        """Coerce a user function's return value back into a block.  Arrow
        tables pass THROUGH — a pyarrow pipeline stays Arrow end-to-end."""
        if batch is None:
            return {}
        if isinstance(batch, dict):
            return {k: v if isinstance(v, np.ndarray) else _column(list(v))
                    for k, v in batch.items()}
        if is_pandas_block(batch):
            return batch  # frames pass through: pandas stays pandas
        try:
            import pyarrow as pa

            if isinstance(batch, pa.Table):
                return batch
        except ImportError:
            pass
        if isinstance(batch, list):
            return BlockAccessor.from_rows(batch)
        raise TypeError(
            f"{what} must return dict[str, np.ndarray], pandas.DataFrame, "
            f"pyarrow.Table, or list[dict]; got {type(batch)}")


def format_batch(block: Block, batch_format: Optional[str]):
    """Present a block to user code in the requested format.

    None/'default' mean dict-of-numpy — the TPU-first canonical layout and
    the pre-Arrow behavior, so existing numpy-style UDFs keep working on
    Arrow-sourced datasets.  Arrow stays Arrow only when asked for
    ('pyarrow'), which is what keeps a parquet pipeline pivot-free."""
    if batch_format in (None, "numpy", "native", "default"):
        return BlockAccessor.to_numpy_block(block)
    if batch_format == "pandas":
        return BlockAccessor.to_pandas(block)
    if batch_format == "pyarrow":
        return BlockAccessor.to_arrow(block)
    raise ValueError(f"unknown batch_format: {batch_format!r}")
