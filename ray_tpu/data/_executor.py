"""Streaming executor: runs a logical plan as remote tasks over the runtime.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48 and
operators/.  Same architecture, pull-driven instead of thread-driven: the
output iterator advances the scheduler each time the consumer asks for a
block, so a slow consumer naturally backpressures the whole pipeline (the
reference uses a scheduler thread + explicit backpressure policies; here the
bounded per-operator in-flight and output queues are the policy).

Map operators stream block->block with bounded in-flight tasks (or a bounded
actor pool for stateful transforms); all-to-all operators (shuffle, sort,
repartition, groupby) materialize their input then fan out map/reduce tasks,
exactly like the reference's push-based shuffle.
"""

from __future__ import annotations

import collections
import logging
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data import _logical as L
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.datasource import ReadTask, write_block

logger = logging.getLogger(__name__)

# Item flowing between operators: (block_ref, BlockMetadata)
RefBundle = Tuple[Any, BlockMetadata]


class DataContext:
    """Execution knobs (reference: data/context.py DataContext)."""

    max_tasks_in_flight_per_op = 8
    max_output_queue_blocks = 16
    target_min_block_size = 1 * 1024 * 1024
    actor_pool_util_threshold = 2  # queued-per-actor before scaling up
    # Explicit memory-budget backpressure (reference:
    # _internal/execution/backpressure_policy/ + resource_manager.py): once
    # the bytes buffered in operator output queues exceed this, no new
    # read/map tasks are admitted until the consumer drains.  The bounded
    # queues cap BLOCK counts; this caps BYTES, which is what actually
    # protects the object store when blocks are large.
    max_buffered_bytes = 512 * 1024 * 1024

    @classmethod
    def get_current(cls) -> "DataContext":
        return _ctx


_ctx = DataContext()


# ------------------------------------------------------- remote helpers

@ray_tpu.remote(num_returns=2)
def _run_read_task(task: ReadTask):
    block = BlockAccessor.concat(task())
    return block, BlockAccessor.metadata(block, task.metadata.input_files)


@ray_tpu.remote(num_returns=2)
def _run_stages(stages: List[L.MapStage], block: Block):
    out = L.apply_stages(stages, block)
    return out, BlockAccessor.metadata(out)


@ray_tpu.remote(num_returns=2)
def _concat_blocks(*blocks):
    out = BlockAccessor.concat(list(blocks))
    return out, BlockAccessor.metadata(out)


@ray_tpu.remote(num_returns=2)
def _slice_block(block: Block, start: int, end: int):
    out = BlockAccessor.slice(block, start, end)
    return out, BlockAccessor.metadata(out)


@ray_tpu.remote
class _MapWorker:
    """Actor-pool worker: instantiates callable-class stages once, then maps
    every dispatched block through them (reference:
    actor_pool_map_operator.py)."""

    def __init__(self, stages: List[L.MapStage]):
        self._stages = stages
        self._fns = [s.instantiate() for s in stages]

    def run(self, block: Block):
        out = L._apply(self._stages, self._fns, block)
        return out, BlockAccessor.metadata(out)


# --------------------------------------------------------- operator states

class _OpState:
    def __init__(self, op: L.LogicalOp, name: str):
        self.op = op
        self.name = name
        self.input: collections.deque = collections.deque()
        self.output: collections.deque = collections.deque()
        self.inflight: Dict[Any, Any] = {}   # block_ref -> (seq, meta_ref, actor)
        # Reorder buffer: tasks finish in any order, but bundles must leave
        # in admission order (reference: preserve_order execution option —
        # here it's always on; repartition/take/files depend on it).
        self.seq_next = 0
        self.emit_fifo: collections.deque = collections.deque()
        self.done_results: Dict[int, Any] = {}
        self.upstream_done = False
        self.done = False
        self.rows_out = 0
        # Rows the executor has yielded to the caller from this op's output.
        # Kept separate from rows_out: Limit uses rows_out as its consumed-row
        # cap, so counting yielded bundles there again would under-emit when
        # input streams in across scheduler iterations.
        self.rows_emitted = 0
        self.tasks_launched = 0
        # Running average output-block size: the in-flight term of the byte
        # budget (seeded at the target block size until real data arrives).
        self.avg_block_bytes = DataContext.target_min_block_size
        self._blocks_seen = 0
        # actor pool
        self.pool: List[Any] = []
        self.pool_busy: Dict[Any, int] = {}

    def shutdown(self):
        for a in self.pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.pool.clear()


class StreamingExecutor:
    def __init__(self, root: L.LogicalOp):
        import time as _time
        import uuid

        from ray_tpu.data._metrics import data_metrics

        self.root = L.optimize(root)
        self.chain = L.plan_to_list(self.root)
        self.states = [_OpState(op, op.name()) for op in self.chain]
        self._stats: Dict[str, Dict[str, Any]] = {}
        # library metrics: short per-executor uid keeps two concurrent
        # pipelines' series distinct; operator label carries the plan index
        # so views render the chain in order
        self._id = uuid.uuid4().hex[:8]
        self._metrics = data_metrics()
        self._pipeline_labels = {"dataset": self._id}
        for i, st in enumerate(self.states):
            st.metric_labels = {"dataset": self._id,
                                "operator": f"{i}:{st.name}"}
        self._gated = False          # byte budget currently throttling reads
        self._last_buffered = 0
        self._gauge_clock = _time.monotonic
        self._last_gauge_ts = 0.0

    def _update_gauges(self, force: bool = False) -> None:
        """Refresh queue/backpressure gauges, throttled: the scheduler loop
        spins per block, but scrapes land every few seconds."""
        now = self._gauge_clock()
        if not force and now - self._last_gauge_ts < 0.2:
            return
        self._last_gauge_ts = now
        m = self._metrics
        for st in self.states:
            m["queue"].set(len(st.output), st.metric_labels)
        m["buffered_bytes"].set(self._last_buffered, self._pipeline_labels)
        m["backpressure"].set(1.0 if self._gated else 0.0,
                              self._pipeline_labels)

    # ------------------------------------------------------------ public
    def execute(self) -> Iterator[RefBundle]:
        """Yield output (block_ref, meta) bundles as they become available."""
        try:
            yield from self._run()
        finally:
            for st in self.states:
                st.shutdown()

    def stats(self) -> Dict[str, Dict[str, Any]]:
        return self._stats

    # ------------------------------------------------------------ engine
    def _run(self) -> Iterator[RefBundle]:
        states = self.states
        # Seed the source operator.
        src = states[0]
        self._seed_source(src)
        final = states[-1]
        while True:
            progressed = False
            # Schedule sinks-first so downstream demand admits upstream work.
            for i in reversed(range(len(states))):
                progressed |= self._schedule_op(i)
            self._drain_completed()
            self._propagate(states)
            self._update_gauges()
            while final.output:
                ref, meta = final.output.popleft()
                final.rows_emitted += meta.num_rows
                yield ref, meta
                progressed = True
            if final.done:
                break
            if not progressed:
                self._wait_any()
        self._gated = False
        self._update_gauges(force=True)
        for st in states:
            self._stats[st.name] = {
                "tasks": st.tasks_launched,
                "rows_out": max(st.rows_out, st.rows_emitted)}

    _BARRIER_OPS = (L.Repartition, L.RandomShuffle, L.Sort, L.GroupByAgg,
                    L.MapGroups, L.RandomizeBlockOrder, L.Zip, L.Union)

    def _buffered_bytes(self) -> int:
        """Bytes the pipeline currently holds: bundles queued in operator
        input/output deques PLUS an estimate for in-flight tasks (launched
        reads/maps land regardless of later admission decisions, so they
        must count against the budget at admission time).  Barrier ops'
        input is EXCLUDED: they materialize their whole input by design, so
        counting it would gate the source forever (livelock) without making
        the materialization any smaller."""
        total = 0
        for st in self.states:
            for item in st.output:
                total += max(item[1].size_bytes, 0)
            if not isinstance(st.op, self._BARRIER_OPS) and st.input and \
                    isinstance(st.input[0], tuple):
                # (Read ops queue ReadTasks, not (ref, meta) bundles)
                for item in st.input:
                    total += max(item[1].size_bytes, 0)
            total += len(st.inflight) * st.avg_block_bytes
        return total

    def _seed_source(self, src: _OpState):
        op = src.op
        if isinstance(op, L.Read):
            tasks = op.datasource.get_read_tasks(op.parallelism)
            for t in tasks:
                src.input.append(t)
        elif isinstance(op, L.InputBlocks):
            for ref, meta in zip(op.refs, op.metas):
                src.output.append((ref, meta))
            src.done = True
        else:
            raise TypeError(f"plan root must be Read/InputBlocks, got {op}")
        src.upstream_done = True

    # ------------------------------------------------- per-op scheduling
    def _schedule_op(self, i: int) -> bool:
        st = self.states[i]
        if st.done:
            return False
        op = st.op
        ctx = _ctx
        downstream_room = (len(st.output) < ctx.max_output_queue_blocks)
        progressed = False

        if isinstance(op, L.Read):
            # The byte budget throttles SOURCES only: bytes enter the
            # pipeline here, and downstream operators must stay free to
            # drain what is already buffered (gating them too would
            # deadlock once the budget trips).  Computed once per pass —
            # the admission burst it allows is bounded by the in-flight cap.
            # Liveness override: if NOTHING is running anywhere, admitting
            # one read is the only way the pipeline can make progress.
            base_bytes = self._buffered_bytes()
            admitted = 0
            forced = False
            if base_bytes >= ctx.max_buffered_bytes and st.input and \
                    not any(s.inflight for s in self.states):
                forced = True
            self._last_buffered = base_bytes
            self._gated = bool(st.input) and \
                base_bytes >= ctx.max_buffered_bytes and not forced
            while (st.input and downstream_room
                   and len(st.inflight) < ctx.max_tasks_in_flight_per_op
                   and (forced or base_bytes + admitted * st.avg_block_bytes
                        < ctx.max_buffered_bytes)):
                task = st.input.popleft()
                bref, mref = _run_read_task.remote(task)
                self._track(st, bref, mref)
                admitted += 1
                progressed = True
                if forced:
                    break  # liveness override admits exactly one read
                downstream_room = len(st.output) < ctx.max_output_queue_blocks
        elif isinstance(op, L.InputBlocks):
            pass
        elif isinstance(op, L.MapOp):
            if op.compute.kind == "actors":
                progressed |= self._schedule_actor_map(st, op)
            else:
                while (st.input and downstream_room
                       and len(st.inflight) < ctx.max_tasks_in_flight_per_op):
                    ref, _meta = st.input.popleft()
                    remote = _run_stages
                    if op.ray_remote_args:
                        remote = remote.options(**op.ray_remote_args)
                    bref, mref = remote.remote(op.stages, ref)
                    self._track(st, bref, mref)
                    progressed = True
        elif isinstance(op, L.Limit):
            while st.input and downstream_room:
                ref, meta = st.input.popleft()
                remaining = op.limit - st.rows_out
                if remaining <= 0:
                    st.done = True
                    break
                if meta.num_rows > remaining:
                    bref, mref = _slice_block.remote(ref, 0, remaining)
                    meta = BlockMetadata(num_rows=remaining, size_bytes=-1,
                                         schema=meta.schema)
                    st.output.append((bref, meta))
                else:
                    st.output.append((ref, meta))
                st.rows_out += meta.num_rows
                progressed = True
            if st.rows_out >= op.limit:
                st.done = True
        elif isinstance(op, self._BARRIER_OPS):
            # Barrier ops: wait for the full input, then run.
            if st.upstream_done and not st.inflight:
                bundles = list(st.input)
                st.input.clear()
                for out in self._run_all_to_all(op, bundles):
                    st.output.append(out)
                    self._metrics["blocks"].inc(1, st.metric_labels)
                    if out[1].num_rows > 0:
                        self._metrics["rows"].inc(out[1].num_rows,
                                                  st.metric_labels)
                st.done = True
                progressed = True
        elif isinstance(op, L.Write):
            while (st.input
                   and len(st.inflight) < ctx.max_tasks_in_flight_per_op):
                ref, _meta = st.input.popleft()
                idx = st.tasks_launched

                def _write(block, idx=idx, op=op):
                    path = write_block(block, op.path, op.fmt, idx,
                                       **op.write_args)
                    b = {"path": np.asarray([path], dtype=object)}
                    return b

                stages = [L.MapStage(kind="batches", fn=_write,
                                     batch_size=None)]
                bref, mref = _run_stages.remote(stages, ref)
                self._track(st, bref, mref)
                progressed = True
        else:
            raise TypeError(f"unknown operator {op}")

        if (st.upstream_done and not st.input and not st.inflight
                and not isinstance(op, (L.Read, L.InputBlocks))):
            st.done = True
        if isinstance(op, L.Read) and not st.input and not st.inflight:
            st.done = True
        return progressed

    def _schedule_actor_map(self, st: _OpState, op: L.MapOp) -> bool:
        progressed = False
        if not st.pool:
            for _ in range(op.compute.min_size):
                self._add_pool_actor(st, op)
        # scale up when the queue builds
        if (len(st.input) > _ctx.actor_pool_util_threshold * len(st.pool)
                and len(st.pool) < op.compute.max_size):
            self._add_pool_actor(st, op)
        downstream_room = len(st.output) < _ctx.max_output_queue_blocks
        while st.input and downstream_room:
            actor = min(st.pool, key=lambda a: st.pool_busy[a])
            if st.pool_busy[actor] >= 2:   # per-actor pipelining depth
                break
            ref, _meta = st.input.popleft()
            bref, mref = actor.run.options(num_returns=2).remote(ref)
            self._track(st, bref, mref, actor)
            st.pool_busy[actor] += 1
            progressed = True
        return progressed

    def _add_pool_actor(self, st: _OpState, op: L.MapOp):
        cls = _MapWorker
        if op.ray_remote_args:
            cls = cls.options(**op.ray_remote_args)
        a = cls.remote(op.stages)
        st.pool.append(a)
        st.pool_busy[a] = 0

    # ----------------------------------------------------------- plumbing
    def _track(self, st: _OpState, bref, mref, actor=None):
        seq = st.seq_next
        st.seq_next += 1
        st.emit_fifo.append(seq)
        st.inflight[bref] = (seq, mref, actor)
        st.tasks_launched += 1
        self._metrics["tasks"].inc(1, st.metric_labels)

    def _drain_completed(self):
        pending = []
        owners = {}
        for st in self.states:
            for bref in st.inflight:
                pending.append(bref)
                owners[bref] = st
        if not pending:
            return
        ready, _ = ray_tpu.wait(pending, num_returns=len(pending), timeout=0)
        for bref in ready:
            st = owners[bref]
            seq, mref, actor = st.inflight.pop(bref)
            if actor is not None:
                st.pool_busy[actor] -= 1
            meta = ray_tpu.get(mref)
            if meta.size_bytes > 0:
                st._blocks_seen += 1
                st.avg_block_bytes += (meta.size_bytes - st.avg_block_bytes) \
                    / st._blocks_seen
            self._metrics["blocks"].inc(1, st.metric_labels)
            if meta.num_rows > 0:
                self._metrics["rows"].inc(meta.num_rows, st.metric_labels)
            st.done_results[seq] = (bref, meta)
            while st.emit_fifo and st.emit_fifo[0] in st.done_results:
                st.output.append(st.done_results.pop(st.emit_fifo.popleft()))

    def _propagate(self, states: List[_OpState]):
        for up, down in zip(states, states[1:]):
            if down.done:
                # e.g. Limit reached: discard upstream surplus
                up.output.clear()
                continue
            while up.output:
                down.input.append(up.output.popleft())
            if up.done:
                down.upstream_done = True

    def _wait_any(self):
        pending = [bref for st in self.states for bref in st.inflight]
        if not pending:
            return
        ray_tpu.wait(pending, num_returns=1, timeout=1.0)

    # -------------------------------------------------------- all-to-all
    def _run_all_to_all(self, op, bundles: List[RefBundle]) -> List[RefBundle]:
        refs = [r for r, _ in bundles]
        metas = [m for _, m in bundles]
        if isinstance(op, L.RandomizeBlockOrder):
            rng = np.random.default_rng(op.seed)
            order = rng.permutation(len(bundles))
            return [bundles[i] for i in order]
        if isinstance(op, L.Repartition):
            return _repartition(refs, metas, op.num_blocks)
        if isinstance(op, L.RandomShuffle):
            n_out = op.num_blocks or max(1, len(refs))
            return _shuffle(refs, n_out, op.seed)
        if isinstance(op, L.Sort):
            return _sort(refs, metas, op.key, op.descending)
        if isinstance(op, L.GroupByAgg):
            return _groupby_agg(refs, op.keys, op.aggs)
        if isinstance(op, L.MapGroups):
            return _map_groups(refs, op.keys, op.fn, op.batch_format)
        if isinstance(op, L.Zip):
            return _zip(refs, metas, op.other)
        if isinstance(op, L.Union):
            out = list(bundles)
            for branch in op.others:
                sub = StreamingExecutor(branch)
                out.extend(sub.execute())
            return out
        raise TypeError(op)


# ------------------------------------------------------ all-to-all kernels

def _repartition(refs, metas, n_out: int) -> List[RefBundle]:
    """Split/merge to exactly n_out blocks preserving order (reference:
    split_repartition — no shuffle)."""
    total = sum(m.num_rows for m in metas)
    per = [total // n_out + (1 if i < total % n_out else 0)
           for i in range(n_out)]
    return _repartition_to(refs, metas, per)


@ray_tpu.remote
def _shuffle_map(block: Block, n_out: int, seed):
    rng = np.random.default_rng(seed)
    n = BlockAccessor.num_rows(block)
    assign = rng.integers(0, n_out, n)
    shards = [BlockAccessor.take_idx(block, np.nonzero(assign == j)[0])
              for j in range(n_out)]
    return shards[0] if n_out == 1 else tuple(shards)


def _scatter(map_fn, refs, n_out: int, extra_args_fn) -> List[List[Any]]:
    """Run map_fn per source block with num_returns=n_out so reducer j pulls
    ONLY shard j from each mapper — O(data) total transfer, not O(n_out x
    data) (reference: push-based shuffle moves each shard exactly once)."""
    per_map = []
    for i, r in enumerate(refs):
        out = map_fn.options(num_returns=n_out).remote(r, *extra_args_fn(i))
        per_map.append([out] if n_out == 1 else list(out))
    return [[m[j] for m in per_map] for j in range(n_out)]


@ray_tpu.remote(num_returns=2)
def _shuffle_reduce(j: int, seed, *shards):
    block = BlockAccessor.concat(list(shards))
    # reduce-side permutation so rows from one source block don't stay adjacent
    rng = np.random.default_rng(None if seed is None else seed + j + 1)
    block = BlockAccessor.take_idx(
        block, rng.permutation(BlockAccessor.num_rows(block)))
    return block, BlockAccessor.metadata(block)


def _shuffle(refs, n_out: int, seed) -> List[RefBundle]:
    by_reducer = _scatter(
        _shuffle_map, refs, n_out,
        lambda i: (n_out, None if seed is None else seed + i))
    out = []
    for j in range(n_out):
        bref, mref = _shuffle_reduce.remote(j, seed, *by_reducer[j])
        out.append((bref, mref))
    return [(b, ray_tpu.get(m)) for b, m in out]


@ray_tpu.remote
def _sort_sample(block: Block, key: str):
    block = BlockAccessor.to_numpy_block(block)  # dict-indexing kernel
    col = block[key]
    k = min(len(col), 64)
    if len(col) == 0:
        return np.asarray([])
    idx = np.linspace(0, len(col) - 1, k).astype(int)
    return np.sort(col)[idx]


@ray_tpu.remote
def _sort_map(block: Block, key: str, bounds):
    block = BlockAccessor.to_numpy_block(block)  # dict-indexing kernel
    col = block[key]
    order = np.argsort(col, kind="stable")
    sorted_block = BlockAccessor.take_idx(block, order)
    cuts = np.searchsorted(sorted_block[key], bounds, side="right")
    parts = []
    prev = 0
    for c in list(cuts) + [BlockAccessor.num_rows(sorted_block)]:
        parts.append(BlockAccessor.slice(sorted_block, prev, c))
        prev = c
    return parts[0] if len(parts) == 1 else tuple(parts)


@ray_tpu.remote(num_returns=2)
def _sort_reduce(j: int, key: str, descending: bool, *parts):
    block = BlockAccessor.to_numpy_block(BlockAccessor.concat(list(parts)))
    order = np.argsort(block.get(key, np.asarray([])), kind="stable") \
        if block else np.asarray([], dtype=int)
    block = BlockAccessor.take_idx(block, order) if block else block
    if descending:
        block = {k: v[::-1] for k, v in block.items()}
    return block, BlockAccessor.metadata(block)


def _sort(refs, metas, key: str, descending: bool) -> List[RefBundle]:
    if not refs:
        return []
    samples = ray_tpu.get([_sort_sample.remote(r, key) for r in refs])
    non_empty = [s for s in samples if len(s)]
    n_out = len(refs)
    if not non_empty:
        # every block is empty: still emit n_out (empty) parts per mapper so
        # the reduce arity matches num_returns
        bounds = np.zeros(max(n_out - 1, 0))
    else:
        allsamp = np.sort(np.concatenate(non_empty))
        idx = np.linspace(0, len(allsamp) - 1, n_out + 1).astype(int)[1:-1]
        bounds = allsamp[idx]
    by_reducer = _scatter(_sort_map, refs, n_out, lambda i: (key, bounds))
    outs = []
    for j in range(n_out):
        bref, mref = _sort_reduce.remote(j, key, descending, *by_reducer[j])
        outs.append((bref, mref))
    bundles = [(b, ray_tpu.get(m)) for b, m in outs]
    if descending:
        bundles = bundles[::-1]
    return bundles


@ray_tpu.remote
def _hash_partition(block: Block, keys: List[str], n_out: int):
    block = BlockAccessor.to_numpy_block(block)  # dict-indexing kernel
    n = BlockAccessor.num_rows(block)
    if n == 0:
        return block if n_out == 1 else tuple([block] * n_out)
    import hashlib

    def stable(x):
        # hash(str) is per-process randomized (PYTHONHASHSEED): partitions
        # computed in different workers MUST agree, so hash content instead.
        # Masked to uint64 range (Python hash() is signed).
        return int.from_bytes(
            hashlib.blake2b(str(x).encode(), digest_size=8).digest(),
            "little")

    mask = (1 << 64) - 1
    h = np.zeros(n, dtype=np.uint64)
    for k in keys:
        col = block[k]
        if col.dtype.kind in "OUS":
            kh = np.asarray([stable(x) for x in col], dtype=np.uint64)
        elif col.dtype.kind in "iu":
            kh = col.astype(np.int64, copy=False).view(np.uint64)
        else:
            kh = np.asarray([hash(float(x)) & mask for x in col],
                            dtype=np.uint64)
        h = h * np.uint64(1000003) + kh
    assign = (h % np.uint64(n_out)).astype(int)
    shards = [BlockAccessor.take_idx(block, np.nonzero(assign == j)[0])
              for j in range(n_out)]
    return shards[0] if n_out == 1 else tuple(shards)


@ray_tpu.remote(num_returns=2)
def _agg_reduce(j: int, keys: List[str], aggs, *parts):
    from ray_tpu.data.aggregate import apply_aggs_to_groups

    block = BlockAccessor.to_numpy_block(BlockAccessor.concat(list(parts)))
    out = apply_aggs_to_groups(block, keys, aggs)
    return out, BlockAccessor.metadata(out)


def _groupby_agg(refs, keys, aggs) -> List[RefBundle]:
    if not refs:
        return []
    # global aggregate (no keys) must reduce in ONE partition: empty hash
    # partitions would otherwise emit spurious init-value rows
    n_out = 1 if not keys else max(1, min(len(refs), 8))
    by_reducer = _scatter(_hash_partition, refs, n_out, lambda i: (keys, n_out))
    outs = []
    for j in range(n_out):
        bref, mref = _agg_reduce.remote(j, keys, aggs, *by_reducer[j])
        outs.append((bref, mref))
    return [(b, ray_tpu.get(m)) for b, m in outs]


@ray_tpu.remote(num_returns=2)
def _map_groups_reduce(j: int, keys, fn, batch_format, *parts):
    from ray_tpu.data.block import format_batch

    block = BlockAccessor.to_numpy_block(BlockAccessor.concat(list(parts)))
    n = BlockAccessor.num_rows(block)
    outs = []
    if n:
        keycols = [block[k] for k in keys]
        tags = [tuple(c[i].item() if hasattr(c[i], "item") else c[i]
                      for c in keycols) for i in range(n)]
        seen = {}
        for i, t in enumerate(tags):
            seen.setdefault(t, []).append(i)
        for t, idxs in seen.items():
            grp = BlockAccessor.take_idx(block, np.asarray(idxs))
            res = fn(format_batch(grp, batch_format))
            outs.append(BlockAccessor.normalize(res, "map_groups"))
    out = BlockAccessor.concat(outs)
    return out, BlockAccessor.metadata(out)


def _map_groups(refs, keys, fn, batch_format) -> List[RefBundle]:
    if not refs:
        return []
    n_out = max(1, min(len(refs), 8))
    by_reducer = _scatter(_hash_partition, refs, n_out, lambda i: (keys, n_out))
    outs = []
    for j in range(n_out):
        bref, mref = _map_groups_reduce.remote(j, keys, fn, batch_format,
                                               *by_reducer[j])
        outs.append((bref, mref))
    return [(b, ray_tpu.get(m)) for b, m in outs]


@ray_tpu.remote(num_returns=2)
def _zip_blocks(a: Block, b: Block):
    a = BlockAccessor.to_numpy_block(a)
    b = BlockAccessor.to_numpy_block(b)
    dup = set(a) & set(b)
    merged = dict(a)
    for k, v in b.items():
        merged[k + "_1" if k in dup else k] = v
    return merged, BlockAccessor.metadata(merged)


def _zip(refs, metas, other_plan) -> List[RefBundle]:
    sub = StreamingExecutor(other_plan)
    other = list(sub.execute())
    total_l = sum(m.num_rows for m in metas)
    total_r = sum(m.num_rows for _, m in other)
    if total_l != total_r:
        raise ValueError(
            f"zip requires equal row counts, got {total_l} vs {total_r}")
    # realign the right side to the left side's EXACT block boundaries
    right = _repartition_to([r for r, _ in other], [m for _, m in other],
                            [m.num_rows for m in metas])
    out = []
    for (lref, _), (rref, _) in zip(zip(refs, metas), right):
        bref, mref = _zip_blocks.remote(lref, rref)
        out.append((bref, mref))
    return [(b, ray_tpu.get(m)) for b, m in out]


def _repartition_to(refs, metas, sizes: List[int]) -> List[RefBundle]:
    out: List[RefBundle] = []
    src, offset = 0, 0
    for want in sizes:
        parts = []
        need = want
        while need > 0 and src < len(refs):
            avail = metas[src].num_rows - offset
            take = min(avail, need)
            parts.append(_slice_block.remote(refs[src], offset, offset + take)[0])
            offset += take
            need -= take
            if offset >= metas[src].num_rows:
                src += 1
                offset = 0
        bref, mref = _concat_blocks.remote(*parts) if parts else \
            _concat_blocks.remote()
        out.append((bref, ray_tpu.get(mref)))
    return out
