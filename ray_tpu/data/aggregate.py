"""Aggregations for Dataset.groupby / Dataset.aggregate.

Reference: python/ray/data/aggregate.py (AggregateFn, Count/Sum/Min/Max/
Mean/Std/AbsMax...).  Each aggregation is (init, accumulate-block, merge,
finalize) so partial aggregation runs remote-side per hash partition.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


class AggregateFn:
    def __init__(self, init: Callable[[], Any],
                 accumulate_block: Callable[[Any, Block], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any] = lambda a: a,
                 name: str = "agg"):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize
        self.name = name


def _col(block: Block, on: Optional[str]):
    if on is None:
        # first numeric column
        for k, v in block.items():
            if v.dtype.kind in "iuf":
                return v
        raise ValueError("no numeric column to aggregate on")
    return block[on]


class Count(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: a + BlockAccessor.num_rows(b),
            merge=lambda a, b: a + b,
            name="count()")


class Sum(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: a + _col(b, on).sum(),
            merge=lambda a, b: a + b,
            name=f"sum({on or ''})")


class Min(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: None,
            accumulate_block=lambda a, b: _col(b, on).min() if a is None
            else min(a, _col(b, on).min()),
            merge=lambda a, b: b if a is None else (a if b is None else min(a, b)),
            name=f"min({on or ''})")


class Max(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: None,
            accumulate_block=lambda a, b: _col(b, on).max() if a is None
            else max(a, _col(b, on).max()),
            merge=lambda a, b: b if a is None else (a if b is None else max(a, b)),
            name=f"max({on or ''})")


class Mean(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: (0.0, 0),
            accumulate_block=lambda a, b: (a[0] + _col(b, on).sum(),
                                           a[1] + len(_col(b, on))),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else None,
            name=f"mean({on or ''})")


class Std(AggregateFn):
    """Welford-style mergeable variance."""

    def __init__(self, on: Optional[str] = None, ddof: int = 1):
        def acc(a, b):
            col = _col(b, on).astype(np.float64)
            if len(col) == 0:
                return a
            chunk_mean = float(col.mean())
            chunk = (len(col), chunk_mean,
                     float(((col - chunk_mean) ** 2).sum()))
            return merge(a, chunk)

        def merge(a, b):
            n1, mean1, m21 = a
            n2, mean2, m22 = b
            if n1 == 0:
                return b
            if n2 == 0:
                return a
            delta = mean2 - mean1
            tot = n1 + n2
            return (tot, mean1 + delta * n2 / tot,
                    m21 + m22 + delta ** 2 * n1 * n2 / tot)

        super().__init__(
            init=lambda: (0, 0.0, 0.0),
            accumulate_block=acc,
            merge=merge,
            finalize=lambda a: float(np.sqrt(a[2] / (a[0] - ddof)))
            if a[0] > ddof else None,
            name=f"std({on or ''})")


class AbsMax(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: max(a, float(np.abs(_col(b, on)).max())),
            merge=lambda a, b: max(a, b),
            name=f"abs_max({on or ''})")


def apply_aggs_to_groups(block: Block, keys: List[str],
                         aggs: List[AggregateFn]) -> Block:
    """Group one (hash-partitioned) block by keys and apply every agg.
    With no keys: global aggregate -> single-row block."""
    n = BlockAccessor.num_rows(block)
    rows = []
    if not keys:
        accs = [a.init() for a in aggs]
        if n:
            accs = [a.accumulate_block(acc, block)
                    for a, acc in zip(aggs, accs)]
        rows.append({a.name: a.finalize(acc) for a, acc in zip(aggs, accs)})
    else:
        if n == 0:
            return {}
        keycols = [block[k] for k in keys]
        groups: Dict[tuple, List[int]] = {}
        for i in range(n):
            groups.setdefault(tuple(c[i] for c in keycols), []).append(i)
        for tag in sorted(groups, key=lambda t: tuple(str(x) for x in t)):
            idxs = np.asarray(groups[tag])
            sub = BlockAccessor.take_idx(block, idxs)
            row = {k: v for k, v in zip(keys, tag)}
            for a in aggs:
                row[a.name] = a.finalize(a.accumulate_block(a.init(), sub))
            rows.append(row)
    return BlockAccessor.from_rows(rows)
