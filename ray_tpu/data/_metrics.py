"""Data library metrics (reference: the ray_data_* series emitted by
data/_internal/stats.py OpRuntimeMetrics; exported here as ray_tpu_data_*).

The streaming executor runs on the driver (or inside a train worker for
streaming splits), so its process pushes these to the nodelet like any
other registry.  Labels: ``dataset`` is a short per-executor uid (two
concurrent pipelines stay distinct), ``operator`` is ``<index>:<name>`` so
a view can render the chain in plan order even when two operators share a
name.
"""

from __future__ import annotations

import threading
from typing import Dict

from ray_tpu._private import metrics as M

_lock = threading.Lock()
_metrics: Dict[str, M.Metric] = {}


def data_metrics() -> Dict[str, M.Metric]:
    global _metrics
    if not _metrics:
        with _lock:
            if not _metrics:
                _metrics = {
                    "rows": M.Counter(
                        "data_rows_output_total",
                        "rows emitted, per dataset/operator"),
                    "blocks": M.Counter(
                        "data_blocks_output_total",
                        "blocks emitted, per dataset/operator"),
                    "tasks": M.Counter(
                        "data_tasks_launched_total",
                        "remote tasks launched, per dataset/operator"),
                    "queue": M.Gauge(
                        "data_output_queue_blocks",
                        "blocks waiting in an operator's output queue"),
                    "buffered_bytes": M.Gauge(
                        "data_buffered_bytes",
                        "bytes buffered across a pipeline (queued + "
                        "in-flight estimate), per dataset"),
                    "backpressure": M.Gauge(
                        "data_backpressure",
                        "1 while the byte budget is gating source "
                        "admission, per dataset"),
                }
    return _metrics
