"""Datasources: how blocks come into (and leave) a Dataset.

Reference: python/ray/data/datasource/ — a ``Datasource`` turns into a list
of ``ReadTask``s at plan time; each ReadTask runs remotely and yields blocks.
Writes are map tasks that consume blocks and persist them.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


class ReadTask:
    """A serializable unit of reading: call it remotely, get blocks back."""

    def __init__(self, fn: Callable[[], Iterable[Block]],
                 metadata: Optional[BlockMetadata] = None):
        self._fn = fn
        self.metadata = metadata or BlockMetadata(num_rows=-1, size_bytes=-1)

    def __call__(self) -> List[Block]:
        return list(self._fn())


class Datasource:
    """Pluggable source. Subclasses implement get_read_tasks(parallelism)."""

    def name(self) -> str:
        return type(self).__name__

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self.n, self.column = n, column

    def estimate_inmemory_data_size(self):
        return self.n * 8

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        n, p = self.n, max(1, min(parallelism, self.n or 1))
        per = (n + p - 1) // p
        for start in range(0, n, per):
            end = min(start + per, n)
            col = self.column

            def read(start=start, end=end):
                yield {col: np.arange(start, end, dtype=np.int64)}

            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=end - start, size_bytes=(end - start) * 8,
                schema={col: "int64"})))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: Sequence[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self.items
        n = len(items)
        p = max(1, min(parallelism, n or 1))
        per = (n + p - 1) // p
        tasks = []
        for start in range(0, n, per):
            chunk = items[start:start + per]

            def read(chunk=chunk):
                yield BlockAccessor.from_rows(
                    [r if isinstance(r, dict) else {"item": r} for r in chunk])

            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=len(chunk), size_bytes=-1)))
        return tasks


class BlocksDatasource(Datasource):
    """Wraps already-materialized in-memory blocks (from_numpy/from_pandas)."""

    def __init__(self, blocks: List[Block]):
        self.blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self.blocks:
            def read(b=b):
                yield b

            tasks.append(ReadTask(read, BlockAccessor.metadata(b)))
        return tasks


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**", "*"), recursive=True)
                if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched: {paths}")
    return out


class FileDatasource(Datasource):
    """Base for per-file readers: one ReadTask per group of files."""

    def __init__(self, paths, **kwargs):
        self.paths = _expand_paths(paths)
        self.kwargs = kwargs

    def read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        p = max(1, min(parallelism, len(self.paths)))
        per = (len(self.paths) + p - 1) // p
        tasks = []
        for i in range(0, len(self.paths), per):
            group = self.paths[i:i + per]

            def read(group=group, self=self):
                for path in group:
                    yield from self.read_file(path)

            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=-1, size_bytes=sum(os.path.getsize(f) for f in group),
                input_files=group)))
        return tasks


class CSVDatasource(FileDatasource):
    def read_file(self, path):
        # stays on pandas: pyarrow.csv infers different dtypes (e.g. date
        # columns), which would silently change existing pipelines; the
        # Arrow-native path is parquet
        import pandas as pd

        yield BlockAccessor.from_pandas(pd.read_csv(path, **self.kwargs))


class JSONDatasource(FileDatasource):
    def read_file(self, path):
        import json

        with open(path) as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                rows = json.load(f)
            else:  # jsonl
                rows = [json.loads(line) for line in f if line.strip()]
        yield BlockAccessor.from_rows(rows)


class ParquetDatasource(FileDatasource):
    def read_file(self, path):
        import pyarrow.parquet as pq

        # stays an Arrow table: schema-carrying blocks flow through
        # map_batches(batch_format="pyarrow") / iter_batches with no pivot
        yield pq.read_table(path, **self.kwargs)


class NumpyDatasource(FileDatasource):
    def read_file(self, path):
        arr = np.load(path)
        yield {self.kwargs.get("column", "data"): arr}


class TextDatasource(FileDatasource):
    def read_file(self, path):
        with open(path, encoding=self.kwargs.get("encoding", "utf-8")) as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(FileDatasource):
    def read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        col = np.empty(1, dtype=object)
        col[0] = data
        yield {"bytes": col, "path": np.asarray([path], dtype=object)}


# ---------------------------------------------------------------- writers

def write_block(block: Block, path_template: str, fmt: str, index: int,
                **kwargs) -> str:
    os.makedirs(os.path.dirname(path_template) or ".", exist_ok=True)
    path = path_template.format(i=index)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(BlockAccessor.to_arrow(block), path, **kwargs)
    elif fmt == "csv":
        BlockAccessor.to_pandas(block).to_csv(path, index=False, **kwargs)
    elif fmt == "json":
        BlockAccessor.to_pandas(block).to_json(
            path, orient="records", lines=True, **kwargs)
    elif fmt == "numpy":
        column = kwargs.pop("column", None)
        nb = BlockAccessor.to_numpy_block(block)
        arr = nb[column] if column else next(iter(nb.values()))
        np.save(path, arr)
    else:
        raise ValueError(f"unknown write format: {fmt}")
    return path
