"""Creation APIs for Datasets (reference: python/ray/data/read_api.py)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

from ray_tpu.data import _logical as L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data.datasource import (BinaryDatasource, BlocksDatasource,
                                     CSVDatasource, Datasource,
                                     ItemsDatasource, JSONDatasource,
                                     NumpyDatasource, ParquetDatasource,
                                     RangeDatasource, TextDatasource)

DEFAULT_PARALLELISM = 8


def read_datasource(datasource: Datasource, *,
                    parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = DEFAULT_PARALLELISM
    return Dataset(L.Read(datasource=datasource, parallelism=parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    ds = range(n, parallelism=parallelism)

    def expand(batch):
        ids = batch["id"]
        data = np.broadcast_to(
            ids.reshape((len(ids),) + (1,) * len(shape)),
            (len(ids),) + tuple(shape)).copy()
        return {"data": data}

    return ds.map_batches(expand)


def from_items(items: Sequence[Any], *, parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = DEFAULT_PARALLELISM
    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def from_blocks(blocks: List[Block]) -> Dataset:
    return read_datasource(BlocksDatasource(blocks),
                           parallelism=len(blocks) or 1)


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]],
               column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return from_blocks([{column: a} for a in arrays])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks([BlockAccessor.from_pandas(df) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks([BlockAccessor.from_arrow(t) for t in tables])


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(CSVDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(JSONDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_parquet(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(ParquetDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(NumpyDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(TextDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(BinaryDatasource(paths, **kwargs),
                           parallelism=parallelism)
