"""Dataset: lazy, streaming, distributed data pipelines.

Reference: python/ray/data/dataset.py:139.  A Dataset is an immutable handle
on a logical plan; transformations append operators, consumption compiles the
plan (fusing map chains) and drives the streaming executor over the actor/
task runtime.  Blocks are dict-of-numpy (see block.py) — the layout that
feeds ``jax.device_put`` directly, which is the point: the terminal consumer
on this stack is a TPU training loop.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import _logical as L
from ray_tpu.data._executor import StreamingExecutor, RefBundle
from ray_tpu.data.aggregate import (AbsMax, AggregateFn, Count, Max, Mean,
                                    Min, Std, Sum)
from ray_tpu.data.block import Block, BlockAccessor, format_batch
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, plan: L.LogicalOp):
        self._plan = plan

    # ===================================================== transformations
    def _map_op(self, stage: L.MapStage, name: str,
                compute: Optional[L.ComputeStrategy] = None,
                **ray_remote_args) -> "Dataset":
        return Dataset(L.MapOp(
            input=self._plan, stages=[stage],
            compute=compute or L.ComputeStrategy(),
            ray_remote_args=ray_remote_args, op_name=name))

    def map(self, fn: Callable, *, compute=None, fn_args=(), fn_kwargs=None,
            **ray_remote_args) -> "Dataset":
        return self._map_op(
            L.MapStage(kind="rows", fn=fn, fn_args=tuple(fn_args),
                       fn_kwargs=fn_kwargs or {}),
            f"Map({getattr(fn, '__name__', 'fn')})", compute,
            **ray_remote_args)

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None, compute=None,
                    fn_args=(), fn_kwargs=None, fn_constructor_args=(),
                    fn_constructor_kwargs=None, concurrency=None,
                    **ray_remote_args) -> "Dataset":
        if concurrency is not None and compute is None:
            if isinstance(concurrency, tuple):
                compute = L.ActorPoolStrategy(min_size=concurrency[0],
                                              max_size=concurrency[1])
            elif isinstance(fn, type):
                compute = L.ActorPoolStrategy(size=concurrency)
        stage = L.MapStage(
            kind="batches", fn=fn, batch_size=batch_size,
            batch_format=batch_format, fn_args=tuple(fn_args),
            fn_kwargs=fn_kwargs or {},
            fn_constructor_args=tuple(fn_constructor_args),
            fn_constructor_kwargs=fn_constructor_kwargs or {})
        return self._map_op(
            stage, f"MapBatches({getattr(fn, '__name__', 'fn')})", compute,
            **ray_remote_args)

    def flat_map(self, fn: Callable, *, compute=None,
                 **ray_remote_args) -> "Dataset":
        return self._map_op(L.MapStage(kind="flat", fn=fn),
                            f"FlatMap({getattr(fn, '__name__', 'fn')})",
                            compute, **ray_remote_args)

    def filter(self, fn: Callable, *, compute=None,
               **ray_remote_args) -> "Dataset":
        return self._map_op(L.MapStage(kind="filter", fn=fn),
                            f"Filter({getattr(fn, '__name__', 'fn')})",
                            compute, **ray_remote_args)

    def add_column(self, col: str, fn: Callable[[Block], np.ndarray],
                   **ray_remote_args) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[col] = np.asarray(fn(batch))
            return batch

        return self._map_op(L.MapStage(kind="batches", fn=add),
                            f"AddColumn({col})", None, **ray_remote_args)

    def drop_columns(self, cols: List[str], **ray_remote_args) -> "Dataset":
        return self._map_op(
            L.MapStage(kind="batches",
                       fn=lambda b: BlockAccessor.drop(b, cols)),
            f"DropColumns({cols})", None, **ray_remote_args)

    def select_columns(self, cols: List[str], **ray_remote_args) -> "Dataset":
        return self._map_op(
            L.MapStage(kind="batches",
                       fn=lambda b: BlockAccessor.select(b, cols)),
            f"SelectColumns({cols})", None, **ray_remote_args)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._map_op(
            L.MapStage(kind="batches",
                       fn=lambda b: {mapping.get(k, k): v
                                     for k, v in b.items()}),
            f"RenameColumns", None)

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        def sample(b):
            n = BlockAccessor.num_rows(b)
            if seed is None:
                rng = np.random.default_rng()
            else:
                # Per-block stream derived from the block CONTENTS: a fixed
                # seed in every map task would draw the identical mask per
                # block (position-correlated, biased sample).  Content-derived
                # entropy keeps seeded runs reproducible on the same data.
                import hashlib

                h = hashlib.blake2b(digest_size=8)
                for k in sorted(b):
                    col = b[k][: min(n, 64)]
                    h.update(col.tobytes() if col.dtype.kind != "O"
                             else repr(col.tolist()).encode())
                rng = np.random.default_rng(
                    [seed, int.from_bytes(h.digest(), "little")])
            keep = rng.random(n) < fraction
            return BlockAccessor.take_idx(b, np.nonzero(keep)[0])

        return self._map_op(L.MapStage(kind="batches", fn=sample),
                            "RandomSample", None)

    # --------------------------------------------------------- all-to-all
    def repartition(self, num_blocks: int, *, shuffle: bool = False) -> "Dataset":
        if shuffle:
            return Dataset(L.RandomShuffle(input=self._plan,
                                           num_blocks=num_blocks))
        return Dataset(L.Repartition(input=self._plan, num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(L.RandomShuffle(input=self._plan, seed=seed))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(L.RandomizeBlockOrder(input=self._plan, seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(L.Sort(input=self._plan, key=key,
                              descending=descending))

    def groupby(self, key: Union[str, List[str]]) -> "GroupedData":
        keys = [key] if isinstance(key, str) else list(key)
        return GroupedData(self, keys)

    def limit(self, limit: int) -> "Dataset":
        return Dataset(L.Limit(input=self._plan, limit=limit))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(L.Union(input=self._plan,
                               others=[o._plan for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(L.Zip(input=self._plan, other=other._plan))

    # ========================================================= aggregates
    def aggregate(self, *aggs: AggregateFn):
        rows = Dataset(L.GroupByAgg(input=self._plan, keys=[],
                                    aggs=list(aggs))).take_all()
        merged: Dict[str, Any] = {}
        for r in rows:
            merged.update(r)
        if len(aggs) == 1:
            return merged.get(aggs[0].name)
        return merged

    def sum(self, on: Optional[str] = None):
        return self.aggregate(Sum(on))

    def min(self, on: Optional[str] = None):
        return self.aggregate(Min(on))

    def max(self, on: Optional[str] = None):
        return self.aggregate(Max(on))

    def mean(self, on: Optional[str] = None):
        return self.aggregate(Mean(on))

    def std(self, on: Optional[str] = None, ddof: int = 1):
        return self.aggregate(Std(on, ddof))

    def unique(self, column: str) -> List[Any]:
        vals = set()
        for batch in self.select_columns([column]).iter_batches():
            vals.update(batch[column].tolist())
        return sorted(vals, key=lambda x: (str(type(x)), x))

    # ======================================================== consumption
    def iter_bundles(self) -> Iterator[RefBundle]:
        ex = StreamingExecutor(self._plan)
        # exposed for stats/backpressure introspection (reference:
        # Dataset.stats() reads the last executor's metrics)
        self._last_executor = ex
        yield from ex.execute()

    def iter_internal_blocks(self) -> Iterator[Block]:
        for ref, _meta in self.iter_bundles():
            yield ray_tpu.get(ref)

    def iterator(self) -> DataIterator:
        return DataIterator(self)

    def iter_rows(self, *, prefetch_blocks: int = 1) -> Iterator[Dict]:
        return self.iterator().iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return self.iterator().iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         drop_last: bool = True, device=None, sharding=None,
                         prefetch: int = 2, dtypes=None) -> Iterator[Any]:
        return self.iterator().iter_jax_batches(
            batch_size=batch_size, drop_last=drop_last, device=device,
            sharding=sharding, prefetch=prefetch, dtypes=dtypes)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False, device=None, dtypes=None,
                           local_shuffle_buffer_size: Optional[int] = None,
                           local_shuffle_seed: Optional[int] = None
                           ) -> Iterator[Any]:
        return self.iterator().iter_torch_batches(
            batch_size=batch_size, drop_last=drop_last, device=device,
            dtypes=dtypes,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def take(self, limit: int = 20) -> List[Dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self, limit: Optional[int] = None) -> List[Dict]:
        out = list(self.iter_rows())
        if limit is not None and len(out) > limit:
            raise ValueError(f"dataset has more than {limit} rows")
        return out

    def take_batch(self, batch_size: int = 20,
                   batch_format: Optional[str] = "numpy"):
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format):
            return batch
        return {}

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def count(self) -> int:
        # fast path: no map/filter ops -> sum block metadata
        return sum(meta.num_rows for _, meta in self.iter_bundles())

    def schema(self) -> Optional[Dict[str, str]]:
        for _, meta in self.iter_bundles():
            if meta.schema is not None:
                return meta.schema
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s) if s else None

    def num_blocks(self) -> int:
        return sum(1 for _ in self.iter_bundles())

    def size_bytes(self) -> int:
        return sum(max(meta.size_bytes, 0) for _, meta in self.iter_bundles())

    def input_files(self) -> List[str]:
        files: List[str] = []
        for _, meta in self.iter_bundles():
            files.extend(meta.input_files)
        return sorted(set(files))

    def stats(self) -> str:
        ex = StreamingExecutor(self._plan)
        for _ in ex.execute():
            pass
        lines = [f"{name}: {info}" for name, info in ex.stats().items()]
        return "\n".join(lines)

    # ========================================================== persist
    def materialize(self) -> "MaterializedDataset":
        refs, metas = [], []
        for ref, meta in self.iter_bundles():
            refs.append(ref)
            metas.append(meta)
        return MaterializedDataset(L.InputBlocks(refs=refs, metas=metas))

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        frames = []
        n = 0
        for block in self.iter_internal_blocks():
            frames.append(BlockAccessor.to_pandas(block))
            n += len(frames[-1])
            if limit is not None and n >= limit:
                break
        if not frames:
            return pd.DataFrame()
        df = pd.concat(frames, ignore_index=True)
        return df.head(limit) if limit is not None else df

    def to_numpy_refs(self) -> List[Any]:
        return [ref for ref, _ in self.iter_bundles()]

    def write_parquet(self, path: str, **kwargs) -> None:
        self._write("parquet", path, "part-{i:05d}.parquet", **kwargs)

    def write_csv(self, path: str, **kwargs) -> None:
        self._write("csv", path, "part-{i:05d}.csv", **kwargs)

    def write_json(self, path: str, **kwargs) -> None:
        self._write("json", path, "part-{i:05d}.json", **kwargs)

    def write_numpy(self, path: str, column: Optional[str] = None, **kwargs):
        self._write("numpy", path, "part-{i:05d}.npy", column=column, **kwargs)

    def _write(self, fmt: str, path: str, template: str, **kwargs) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        plan = L.Write(input=self._plan, fmt=fmt,
                       path=os.path.join(path, template), write_args=kwargs)
        for _ in StreamingExecutor(plan).execute():
            pass

    # ============================================================ splits
    def split(self, n: int, *, equal: bool = False) -> List["MaterializedDataset"]:
        mat = self.materialize()
        bundles = list(zip(mat._plan.refs, mat._plan.metas))
        total = sum(m.num_rows for _, m in bundles)
        if equal:
            per = total // n
            sizes = [per] * n
        else:
            sizes = [total // n + (1 if i < total % n else 0)
                     for i in range(n)]
        from ray_tpu.data._executor import _repartition_to

        refs = [r for r, _ in bundles]
        metas = [m for _, m in bundles]
        pieces = _repartition_to(refs, metas, sizes)
        return [MaterializedDataset(L.InputBlocks(refs=[r], metas=[m]))
                for r, m in pieces]

    def split_at_indices(self, indices: List[int]) -> List["MaterializedDataset"]:
        mat = self.materialize()
        total = sum(m.num_rows for m in mat._plan.metas)
        bounds = [0] + list(indices) + [total]
        sizes = [max(0, b - a) for a, b in zip(bounds, bounds[1:])]
        from ray_tpu.data._executor import _repartition_to

        pieces = _repartition_to(mat._plan.refs, mat._plan.metas, sizes)
        return [MaterializedDataset(L.InputBlocks(refs=[r], metas=[m]))
                for r, m in pieces]

    def split_proportionately(self, proportions: List[float]) -> List["MaterializedDataset"]:
        if not proportions or any(p <= 0 for p in proportions) \
                or sum(proportions) >= 1:
            raise ValueError("proportions must be positive and sum to < 1")
        total = self.count()
        idx, acc = [], 0.0
        for p in proportions:
            acc += p
            idx.append(int(total * acc))
        return self.split_at_indices(idx)

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        train, test = ds.split_proportionately([1 - test_size])
        return train, test

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[DataIterator]:
        """n coordinated iterators over one shared executor (reference:
        Dataset.streaming_split / StreamSplitDataIterator).  equal=True
        delivers exactly total//n rows to every iterator (lockstep SPMD
        consumers).  locality_hints is accepted for API compatibility; block
        placement is owner-local here, so it has no effect."""
        from ray_tpu.data.iterator import build_streaming_split

        return build_streaming_split(self, n, equal=equal)

    def __repr__(self):
        names = [op.name() for op in L.plan_to_list(self._plan)]
        return f"Dataset(plan={' -> '.join(names)})"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already computed and held by refs."""

    @property
    def _refs(self):
        return self._plan.refs


class GroupedData:
    """Reference: python/ray/data/grouped_data.py."""

    def __init__(self, ds: Dataset, keys: List[str]):
        self._ds = ds
        self._keys = keys

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return Dataset(L.GroupByAgg(input=self._ds._plan, keys=self._keys,
                                    aggs=list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: Optional[str] = None, ddof: int = 1) -> Dataset:
        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn: Callable, *,
                   batch_format: Optional[str] = "numpy") -> Dataset:
        return Dataset(L.MapGroups(input=self._ds._plan, keys=self._keys,
                                   fn=fn, batch_format=batch_format))
