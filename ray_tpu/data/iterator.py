"""DataIterator: batched consumption of a Dataset, including the TPU path.

Reference: python/ray/data/iterator.py (DataIterator.iter_batches /
iter_torch_batches) and _internal/execution/streaming_split coordination.
The TPU-first addition is ``iter_jax_batches``: numeric columns go host ->
device with a prefetch queue so the next batch's transfer overlaps the
current step's compute, optionally placed under a ``jax.sharding`` for a
multi-device mesh.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, format_batch


class DataIterator:
    def __init__(self, ds_or_source):
        self._source = ds_or_source

    def _iter_blocks(self) -> Iterator[Block]:
        src = self._source
        if hasattr(src, "iter_internal_blocks"):
            yield from src.iter_internal_blocks()
        else:
            yield from src()

    # ------------------------------------------------------------- rows
    def iter_rows(self) -> Iterator[Dict]:
        for block in self._iter_blocks():
            yield from BlockAccessor.iter_rows(block)

    # ------------------------------------------------------------ batches
    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        rng = np.random.default_rng(local_shuffle_seed)
        for block in _rebatch(self._iter_blocks(), batch_size, drop_last,
                              local_shuffle_buffer_size, rng):
            yield format_batch(block, batch_format)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         drop_last: bool = True, device=None, sharding=None,
                         prefetch: int = 2, dtypes=None) -> Iterator[Any]:
        import jax

        def put(batch: Block):
            batch = BlockAccessor.to_numpy_block(batch)
            out = {}
            for k, v in batch.items():
                if v.dtype.kind == "O":
                    out[k] = v          # leave object columns on host
                    continue
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                if sharding is not None:
                    out[k] = jax.device_put(v, sharding)
                elif device is not None:
                    out[k] = jax.device_put(v, device)
                else:
                    out[k] = jax.device_put(v)
            return out

        # Depth-`prefetch` pipeline: device transfers for upcoming batches are
        # issued before the current batch is consumed, hiding host->HBM copy
        # behind step compute.
        queue: collections.deque = collections.deque()
        it = _rebatch(self._iter_blocks(), batch_size, drop_last, None, None)
        for batch in it:
            queue.append(put(batch))
            if len(queue) > prefetch:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False, device=None,
                           dtypes=None,
                           local_shuffle_buffer_size: Optional[int] = None,
                           local_shuffle_seed: Optional[int] = None
                           ) -> Iterator[Any]:
        """Batches as dict[str, torch.Tensor] (reference:
        data/iterator.py iter_torch_batches) — numeric columns become
        tensors (optionally moved to ``device`` / cast via ``dtypes``),
        object columns stay numpy."""
        import torch

        rng = np.random.default_rng(local_shuffle_seed)
        for block in _rebatch(self._iter_blocks(), batch_size, drop_last,
                              local_shuffle_buffer_size, rng):
            batch = BlockAccessor.to_numpy_block(block)
            out = {}
            for k, v in batch.items():
                if v.dtype.kind == "O":
                    out[k] = v
                    continue
                t = torch.from_numpy(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    def materialize(self):
        blocks = list(self._iter_blocks())
        from ray_tpu.data import from_blocks

        return from_blocks(blocks)


def _rebatch(blocks: Iterator[Block], batch_size: Optional[int],
             drop_last: bool, shuffle_buffer: Optional[int],
             rng) -> Iterator[Block]:
    """Slice/stitch a block stream into exact-size batches.

    Shuffle path: the buffer is merged + permuted once per REFILL and then
    emitted as slices — permuting the whole buffer per emitted batch would
    cost O(buffer) memcpy per batch (reference: shuffling batcher semantics).
    """
    if batch_size is None:
        yield from (b for b in blocks if BlockAccessor.num_rows(b))
        return
    buf: List[Block] = []
    buffered = 0
    min_buf = shuffle_buffer or 0
    for block in blocks:
        n = BlockAccessor.num_rows(block)
        if n == 0:
            continue
        buf.append(block)
        buffered += n
        if buffered >= batch_size + min_buf:
            merged = BlockAccessor.concat(buf)
            if shuffle_buffer:
                perm = rng.permutation(BlockAccessor.num_rows(merged))
                merged = BlockAccessor.take_idx(merged, perm)
            # emit whole batches down to the shuffle floor, keep the tail
            pos = 0
            total = BlockAccessor.num_rows(merged)
            while total - pos >= batch_size + min_buf:
                yield BlockAccessor.slice(merged, pos, pos + batch_size)
                pos += batch_size
            rest = BlockAccessor.slice(merged, pos, total)
            buf = [rest] if BlockAccessor.num_rows(rest) else []
            buffered = total - pos
    if buffered:
        merged = BlockAccessor.concat(buf)
        if shuffle_buffer:
            perm = rng.permutation(BlockAccessor.num_rows(merged))
            merged = BlockAccessor.take_idx(merged, perm)
        pos = 0
        total = BlockAccessor.num_rows(merged)
        while total - pos >= batch_size:
            yield BlockAccessor.slice(merged, pos, pos + batch_size)
            pos += batch_size
        if pos < total and not drop_last:
            yield BlockAccessor.slice(merged, pos, total)


# ===================================================== streaming split

@ray_tpu.remote
class _SplitCoordinator:
    """Runs ONE streaming executor and deals its output blocks to n
    consumers (reference: StreamSplitDataIterator's SplitCoordinator actor).
    Each consumer may live in a different process (Train workers)."""

    def __init__(self, plan_blob: bytes, n: int, equal: bool):
        import cloudpickle

        self._plan = cloudpickle.loads(plan_blob)
        self._n = n
        self._equal = equal
        self._queues = [collections.deque() for _ in range(n)]
        self._rows = [0] * n
        self._delivered = [0] * n
        self._gen = None
        self._epoch = -1
        self._exhausted = False
        self._rebalanced = False

    def _ensure_epoch(self, epoch: int, split_idx: int) -> bool:
        """Returns True when the requested epoch is active.  The epoch flips
        only once the CURRENT one is fully delivered (generator exhausted and
        every queue drained) — flipping on the first request would wipe
        slower consumers' undelivered queues mid-epoch (lost/duplicated rows,
        desynced SPMD workers).  Serial consumers still work: by the time one
        asks for the next epoch serially, the previous one is complete."""
        if epoch <= self._epoch:
            return True
        if self._epoch >= 0 and not (
                self._exhausted and all(not q for q in self._queues)):
            return False  # stragglers still draining the previous epoch
        from ray_tpu.data._executor import StreamingExecutor

        self._gen = StreamingExecutor(self._plan).execute()
        self._epoch = epoch
        self._exhausted = False
        self._rebalanced = False
        for q in self._queues:
            q.clear()
        self._rows = [0] * self._n
        self._delivered = [0] * self._n
        return True

    def _deal_until(self, split_idx: int, want: int):
        q = self._queues[split_idx]
        while len(q) < want and not self._exhausted:
            try:
                ref, meta = next(self._gen)
            except StopIteration:
                self._exhausted = True
                break
            # deal to the consumer with the fewest rows so far, so splits stay
            # balanced even when consumers pull at different rates
            tgt = min(range(self._n), key=lambda i: self._rows[i])
            self._queues[tgt].append((ref, meta.num_rows))
            self._rows[tgt] += meta.num_rows

    def get_next(self, split_idx: int, epoch: int):
        """Return (block_ref, num_rows), the string "wait" (epoch barrier not
        passed yet — caller retries), or None when the epoch is done."""
        if not self._ensure_epoch(epoch, split_idx):
            return "wait"
        q = self._queues[split_idx]
        # equal=True holds back one block per consumer until the stream's total
        # is known, then rebalances so every split delivers EXACTLY total//n
        # rows (reference: OutputSplitter equal=True — lockstep SPMD consumers
        # need identical batch counts or they deadlock in collectives).
        self._deal_until(split_idx, 2 if self._equal else 1)
        if self._equal and self._exhausted and not self._rebalanced:
            self._rebalance_equal()
        if not q:
            return None
        item = q.popleft()
        self._delivered[split_idx] += item[1]
        return item

    def _rebalance_equal(self):
        """One-time end-of-stream redistribution: pool every undelivered block
        and re-deal so each consumer ends at exactly T = total_rows // n,
        slicing blocks at the boundaries (surplus rows are dropped)."""
        from ray_tpu.data._executor import _slice_block

        self._rebalanced = True
        pool = collections.deque()
        for q in self._queues:
            pool.extend(q)
            q.clear()
        pool_rows = sum(r for _, r in pool)
        total = sum(self._delivered) + pool_rows
        target = max(total // self._n, max(self._delivered))
        for i in range(self._n):
            need = target - self._delivered[i]
            while need > 0 and pool:
                ref, rows = pool.popleft()
                if rows <= need:
                    self._queues[i].append((ref, rows))
                    need -= rows
                else:
                    head, _m = _slice_block.remote(ref, 0, need)
                    tail, _m2 = _slice_block.remote(ref, need, rows)
                    self._queues[i].append((head, need))
                    pool.appendleft((tail, rows - need))
                    need = 0


class _SplitIterator(DataIterator):
    def __init__(self, coord, idx: int):
        self._coord = coord
        self._idx = idx
        self._epoch = -1
        super().__init__(self._pull_blocks)

    def _pull_blocks(self):
        import time

        self._epoch += 1
        while True:
            item = ray_tpu.get(
                self._coord.get_next.remote(self._idx, self._epoch))
            if item is None:
                return
            if item == "wait":  # epoch barrier: others still draining
                time.sleep(0.05)
                continue
            ref, _rows = item
            yield ray_tpu.get(ref)


def build_streaming_split(ds, n: int, *, equal: bool = False):
    import cloudpickle

    coord = _SplitCoordinator.remote(cloudpickle.dumps(ds._plan), n, equal)
    return [_SplitIterator(coord, i) for i in range(n)]
