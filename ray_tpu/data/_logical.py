"""Logical plan for ray_tpu.data: operators, fusion, and block transforms.

Reference: python/ray/data/_internal/logical/ (operators + optimizer rules)
and _internal/planner/.  The key optimization is the same one the reference's
``OperatorFusionRule`` does: consecutive row/batch-level maps collapse into a
single remote task per block, so a ``map().filter().map_batches()`` chain
costs one task launch and zero intermediate materialization.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, format_batch


@dataclass
class ComputeStrategy:
    """tasks (default) or a bounded actor pool."""

    kind: str = "tasks"  # "tasks" | "actors"
    min_size: int = 1
    max_size: int = 1


def ActorPoolStrategy(size: Optional[int] = None, *, min_size: int = 1,
                      max_size: Optional[int] = None) -> ComputeStrategy:
    if size is not None:
        min_size = max_size = size
    return ComputeStrategy("actors", min_size, max_size or max(min_size, 2))


# ------------------------------------------------------------- stage model

@dataclass
class MapStage:
    """One user transform inside a (possibly fused) map chain."""

    kind: str                      # "rows" | "batches" | "filter" | "flat"
    fn: Any                        # callable or callable *class*
    batch_size: Optional[int] = None
    batch_format: Optional[str] = None
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = field(default_factory=dict)

    def instantiate(self) -> Callable:
        """Resolve a callable-class stage to a bound instance (once per
        worker/actor, so expensive setup like model loading amortizes)."""
        if isinstance(self.fn, type):
            inst = self.fn(*self.fn_constructor_args,
                           **self.fn_constructor_kwargs)
            return inst
        return self.fn


def apply_stages(stages: List[MapStage], block: Block) -> Block:
    """Run a fused chain of stages over one block (remote-side hot path)."""
    instantiated = [s.instantiate() for s in stages]
    return _apply(stages, instantiated, block)


def _apply(stages: List[MapStage], fns: List[Callable], block: Block) -> Block:
    for stage, fn in zip(stages, fns):
        n = BlockAccessor.num_rows(block)
        if stage.kind == "batches":
            bs = stage.batch_size
            pieces = []
            for start in range(0, max(n, 1), bs or max(n, 1)):
                batch = BlockAccessor.slice(block, start, min(start + (bs or n), n)) \
                    if n else block
                out = fn(format_batch(batch, stage.batch_format),
                         *stage.fn_args, **stage.fn_kwargs)
                pieces.append(BlockAccessor.normalize(out))
                if not n:
                    break
            block = BlockAccessor.concat(pieces) if pieces else {}
        elif stage.kind == "rows":
            rows = [fn(r, *stage.fn_args, **stage.fn_kwargs)
                    for r in BlockAccessor.iter_rows(block)]
            block = BlockAccessor.from_rows(rows)
        elif stage.kind == "filter":
            keep = np.fromiter(
                (bool(fn(r, *stage.fn_args, **stage.fn_kwargs))
                 for r in BlockAccessor.iter_rows(block)),
                dtype=bool, count=n)
            block = BlockAccessor.take_idx(block, np.nonzero(keep)[0])
        elif stage.kind == "flat":
            rows = []
            for r in BlockAccessor.iter_rows(block):
                rows.extend(fn(r, *stage.fn_args, **stage.fn_kwargs))
            block = BlockAccessor.from_rows(rows)
        else:
            raise ValueError(stage.kind)
    return block


# ------------------------------------------------------------ logical ops

@dataclass
class LogicalOp:
    input: Optional["LogicalOp"] = None

    def name(self) -> str:
        return type(self).__name__


@dataclass
class Read(LogicalOp):
    datasource: Any = None
    parallelism: int = -1

    def name(self):
        return f"Read{self.datasource.name()}"


@dataclass
class InputBlocks(LogicalOp):
    """Already-executed blocks (a MaterializedDataset's plan root)."""

    refs: List[Any] = field(default_factory=list)
    metas: List[Any] = field(default_factory=list)


@dataclass
class MapOp(LogicalOp):
    stages: List[MapStage] = field(default_factory=list)
    compute: ComputeStrategy = field(default_factory=ComputeStrategy)
    ray_remote_args: Dict[str, Any] = field(default_factory=dict)
    op_name: str = "Map"

    def name(self):
        return self.op_name


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1
    shuffle: bool = False


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None
    num_blocks: Optional[int] = None


@dataclass
class RandomizeBlockOrder(LogicalOp):
    seed: Optional[int] = None


@dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclass
class GroupByAgg(LogicalOp):
    keys: List[str] = field(default_factory=list)
    aggs: List[Any] = field(default_factory=list)   # AggregateFn list


@dataclass
class MapGroups(LogicalOp):
    keys: List[str] = field(default_factory=list)
    fn: Any = None
    batch_format: Optional[str] = None


@dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclass
class Union(LogicalOp):
    others: List[LogicalOp] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: Optional[LogicalOp] = None


@dataclass
class Write(LogicalOp):
    fmt: str = ""
    path: str = ""
    write_args: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------- optimizer

def _fusable(a: MapOp, b: MapOp) -> bool:
    """Two adjacent map chains fuse when they'd run on the same workers."""
    if a.compute.kind != b.compute.kind:
        return False
    if a.compute.kind == "actors":
        # different pool shapes must not merge (sizes are user-visible)
        if (a.compute.min_size, a.compute.max_size) != \
           (b.compute.min_size, b.compute.max_size):
            return False
    return a.ray_remote_args == b.ray_remote_args


def optimize(op: LogicalOp) -> LogicalOp:
    """Bottom-up fusion of consecutive MapOps (reference: OperatorFusionRule,
    python/ray/data/_internal/logical/rules/operator_fusion.py)."""
    if op is None:
        return None
    op = copy.copy(op)
    op.input = optimize(op.input)
    if isinstance(op, Union):
        op.others = [optimize(o) for o in op.others]
    if isinstance(op, Zip):
        op.other = optimize(op.other)
    if isinstance(op, MapOp) and isinstance(op.input, MapOp) \
            and _fusable(op.input, op):
        parent = op.input
        return replace(parent,
                       stages=parent.stages + op.stages,
                       op_name=f"{parent.op_name}->{op.op_name}",
                       input=parent.input)
    return op


def plan_to_list(op: LogicalOp) -> List[LogicalOp]:
    """Linear chain root-first (Union/Zip branches hang off their op)."""
    out = []
    while op is not None:
        out.append(op)
        op = op.input
    return list(reversed(out))
