"""ray_tpu.data — streaming, lazy, distributed datasets.

TPU-native counterpart of Ray Data (reference: python/ray/data/): the same
lazy logical-plan / streaming-executor architecture, with dict-of-numpy
blocks as the canonical format so data flows shared-memory store ->
``jax.device_put`` without row pivots, and ``iter_jax_batches`` /
``streaming_split`` feeding per-host TPU training loops.
"""

from ray_tpu.data._logical import ActorPoolStrategy
from ray_tpu.data.aggregate import (AbsMax, AggregateFn, Count, Max, Mean,
                                    Min, Std, Sum)
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (from_arrow, from_blocks, from_items,
                                   from_numpy, from_pandas, range,
                                   range_tensor, read_binary_files, read_csv,
                                   read_datasource, read_json, read_numpy,
                                   read_parquet, read_text)

__all__ = [
    "ActorPoolStrategy", "AggregateFn", "Count", "Sum", "Min", "Max", "Mean",
    "Std", "AbsMax", "Block", "BlockAccessor", "BlockMetadata", "Dataset",
    "GroupedData", "MaterializedDataset", "Datasource", "ReadTask",
    "DataIterator", "from_arrow", "from_blocks", "from_items", "from_numpy",
    "from_pandas", "range", "range_tensor", "read_binary_files", "read_csv",
    "read_datasource", "read_json", "read_numpy", "read_parquet", "read_text",
]
