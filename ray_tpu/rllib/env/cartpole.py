"""Native vectorized CartPole-v1 (no gym in the TPU image).

Standard cart-pole physics (Barto, Sutton & Anderson 1983; identical
constants/termination/reward semantics to Gymnasium's CartPole-v1 so the
BASELINE "return >= 350 within 200k steps" row is comparable): reward 1 per
step, termination at |x| > 2.4 or |theta| > 12 deg, truncation at 500 steps,
Euler integration with tau = 0.02.

Vectorized over K envs in numpy with auto-reset — env stepping stays on the
CPU actor (SURVEY §3.5: EnvRunners stay on CPU; the Learner is the device
program).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class CartPoleVectorEnv:
    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5  # half pole length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    X_THRESHOLD = 2.4
    THETA_THRESHOLD = 12 * 2 * np.pi / 360

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float32)
        self.steps = np.zeros(num_envs, np.int32)
        self.reset()

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, (n, 4)).astype(np.float32)

    def reset(self) -> np.ndarray:
        self.state = self._sample_state(self.num_envs)
        self.steps[:] = 0
        return self.state.copy()

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, dict]:
        """actions: (K,) in {0,1}.  Returns (obs, rewards, terminated,
        truncated, info); terminated/truncated envs are auto-reset — the
        returned obs is the FIRST obs of the next episode for those slots.
        info["final_obs"] holds the true pre-reset observation (valid at done
        slots), which time-limit bootstrapping needs at truncations."""
        x, x_dot, theta, theta_dot = self.state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = np.cos(theta)
        sintheta = np.sin(theta)
        temp = (force + self.POLEMASS_LENGTH * theta_dot**2 * sintheta) \
            / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta**2 / self.TOTAL_MASS))
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta \
            / self.TOTAL_MASS
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self.state = np.stack([x, x_dot, theta, theta_dot], axis=1) \
            .astype(np.float32)
        self.steps += 1

        terminated = (np.abs(x) > self.X_THRESHOLD) \
            | (np.abs(theta) > self.THETA_THRESHOLD)
        truncated = (self.steps >= self.max_episode_steps) & ~terminated
        rewards = np.ones(self.num_envs, np.float32)

        done = terminated | truncated
        final_obs = self.state.copy()
        if done.any():
            self.state[done] = self._sample_state(int(done.sum()))
            self.steps[done] = 0
        return (self.state.copy(), rewards, terminated, truncated,
                {"final_obs": final_obs})
