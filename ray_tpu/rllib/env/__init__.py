"""Environments + the env registry.

The reference resolves env names through gym (rllib/env/utils.py); this image
has no gym, so envs register natively.  The registry maps a name to a
``(num_envs, seed) -> VectorEnv`` factory.
"""

from typing import Callable, Dict

_ENV_REGISTRY: Dict[str, Callable] = {}


def register_env(name: str, creator: Callable) -> None:
    """reference: ray.tune.register_env."""
    _ENV_REGISTRY[name] = creator


def make_vector_env(name: str, num_envs: int, seed: int = 0):
    if name not in _ENV_REGISTRY:
        raise ValueError(
            f"unknown env {name!r}; registered: {sorted(_ENV_REGISTRY)}")
    return _ENV_REGISTRY[name](num_envs=num_envs, seed=seed)


def _register_builtins():
    from ray_tpu.rllib.env.cartpole import CartPoleVectorEnv
    from ray_tpu.rllib.env.pendulum import PendulumVectorEnv

    register_env("CartPole-v1",
                 lambda num_envs, seed=0: CartPoleVectorEnv(num_envs, seed=seed))
    register_env("Pendulum-v1",
                 lambda num_envs, seed=0: PendulumVectorEnv(num_envs, seed=seed))


_register_builtins()
