"""EnvRunner: CPU actor that samples fixed-length rollout fragments.

Counterpart of the reference's SingleAgentEnvRunner (reference:
rllib/env/single_agent_env_runner.py:131 sample; EnvRunnerGroup
rllib/env/env_runner_group.py:71).  Each runner owns K vectorized envs and a
copy of the policy params; ``sample()`` returns time-major arrays
(T, K, ...) plus the value bootstrap for each fragment tail, ready for the
Learner's GAE scan — no per-episode postprocessing on the driver
(the reference's GAE-on-learner new-stack layout).
"""

from __future__ import annotations

import collections
from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import DiscretePolicyModule
from ray_tpu.rllib.env import make_vector_env


class EnvRunner:
    def __init__(self, env_name: str, num_envs: int, rollout_length: int,
                 module_spec: Dict, seed: int = 0):
        # Rollouts are a HOST program: policy inference here is tiny and
        # latency-bound, so pin this process to the CPU backend.  Without
        # this, the TPU-VM site hook pins jax at the device backend and every
        # per-step dispatch crosses to the chip (observed: 270x slower).
        # The Learner is the device program, not the runner (SURVEY §3.5).
        # Exception: if this process already initialized a jax backend (local
        # debug mode sharing the driver with a learner), re-pinning is
        # impossible — keep the existing backend and say so.
        import sys

        if "jax" in sys.modules:
            import jax._src.xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        else:
            initialized = False
        if initialized:
            import logging

            logging.getLogger(__name__).warning(
                "EnvRunner created after the jax backend initialized; "
                "rollout inference shares that backend (use actor "
                "env-runners for the CPU-rollout/device-learner split)")
        else:
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax

        self.env = make_vector_env(env_name, num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.module = DiscretePolicyModule(**module_spec)
        self.params = None
        self._key = jax.random.PRNGKey(seed)
        self.obs = self.env.reset()
        # episode-return bookkeeping (reference: metrics on the EnvRunner)
        self._ep_return = np.zeros(num_envs, np.float32)
        self._recent_returns: collections.deque = collections.deque(maxlen=100)
        self._lifetime_steps = 0

        self._explore = jax.jit(self.module.forward_exploration)
        self._value = jax.jit(self.module.value)

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self, weights=None) -> Dict[str, np.ndarray]:
        """One fragment of rollout_length steps across all K envs."""
        import jax

        if weights is not None:
            self.params = weights
        assert self.params is not None, "set_weights before sample"
        T, K = self.rollout_length, self.num_envs
        out = {
            "obs": np.empty((T, K, self.env.observation_size), np.float32),
            "actions": np.empty((T, K), np.int32),
            "logp": np.empty((T, K), np.float32),
            "values": np.empty((T, K), np.float32),
            "rewards": np.empty((T, K), np.float32),
            "terminated": np.empty((T, K), bool),
            "truncated": np.empty((T, K), bool),
        }
        final_obs = np.empty((T, K, self.env.observation_size), np.float32)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            actions, logp, values = self._explore(self.params, self.obs, sub)
            actions = np.asarray(actions)
            out["obs"][t] = self.obs
            out["actions"][t] = actions
            out["logp"][t] = np.asarray(logp)
            out["values"][t] = np.asarray(values)
            next_obs, rewards, terminated, truncated, info = \
                self.env.step(actions)
            out["rewards"][t] = rewards
            out["terminated"][t] = terminated
            out["truncated"][t] = truncated
            final_obs[t] = info["final_obs"]

            self._ep_return += rewards
            for i in np.nonzero(terminated | truncated)[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self.obs = next_obs
        self._lifetime_steps += T * K

        # next_values[t] = V of the TRUE successor state: values[t+1] inside
        # an episode, V(obs after the fragment) at the tail, 0 at termination,
        # V(pre-reset final obs) at truncation (time-limit bootstrapping —
        # truncation is not failure, the episode just stopped being observed).
        tail_value = np.asarray(self._value(self.params, self.obs))
        next_values = np.concatenate(
            [out["values"][1:], tail_value[None]], axis=0)
        next_values[out["terminated"]] = 0.0
        if out["truncated"].any():
            # evaluate on the full fixed (T*K, obs) shape and index after:
            # a data-dependent batch (the truncation count) would recompile
            # the jit for every distinct count
            tr = np.nonzero(out["truncated"])
            v_final = np.asarray(self._value(
                self.params, final_obs.reshape(T * K, -1))).reshape(T, K)
            next_values[tr] = v_final[tr]
        out["next_values"] = next_values.astype(np.float32)
        return out

    def get_metrics(self) -> Dict:
        return {
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "num_episodes": len(self._recent_returns),
            "num_env_steps_sampled_lifetime": self._lifetime_steps,
        }

    def ping(self) -> bool:
        return True
