"""EnvRunner: CPU actor that samples fixed-length rollout fragments.

Counterpart of the reference's SingleAgentEnvRunner (reference:
rllib/env/single_agent_env_runner.py:131 sample; EnvRunnerGroup
rllib/env/env_runner_group.py:71).  Each runner owns K vectorized envs and a
copy of the policy params; ``sample()`` returns time-major arrays
(T, K, ...) plus the value bootstrap for each fragment tail, ready for the
Learner's GAE scan — no per-episode postprocessing on the driver
(the reference's GAE-on-learner new-stack layout).

Podracer extensions (rllib/podracer/):

- ``run_stream(n)`` is the continuous sample loop: a
  ``num_returns="streaming"`` generator that seals each fragment into
  plasma as it is produced, polling the job's weight mailbox between
  fragments so no weight pytree ever rides a task argument;
- with an ``inference`` pool handle the runner is a *Sebulba* actor: it
  performs ZERO local forward passes — every action, logp and bootstrap
  value comes from the pool's batched forwards, and fragments carry the
  policy version the pool stamped on the responses.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import DiscretePolicyModule
from ray_tpu.rllib.env import make_vector_env


class EnvRunner:
    def __init__(self, env_name: str, num_envs: int, rollout_length: int,
                 module_spec: Dict, seed: int = 0, job: str = "",
                 runner_idx: int = 0, inference=None):
        # Rollouts are a HOST program: policy inference here is tiny and
        # latency-bound, so pin this process to the CPU backend.  Without
        # this, the TPU-VM site hook pins jax at the device backend and every
        # per-step dispatch crosses to the chip (observed: 270x slower).
        # The Learner is the device program, not the runner (SURVEY §3.5).
        # Exception: if this process already initialized a jax backend (local
        # debug mode sharing the driver with a learner), re-pinning is
        # impossible — keep the existing backend and say so.
        import sys

        if "jax" in sys.modules:
            import jax._src.xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        else:
            initialized = False
        if initialized:
            import logging

            logging.getLogger(__name__).warning(
                "EnvRunner created after the jax backend initialized; "
                "rollout inference shares that backend (use actor "
                "env-runners for the CPU-rollout/device-learner split)")
        else:
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax

        self.env = make_vector_env(env_name, num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.module = DiscretePolicyModule(**module_spec)
        self.params = None
        self._key = jax.random.PRNGKey(seed)
        self.obs = self.env.reset()
        self.job = job
        self.runner_idx = runner_idx
        self._pool = inference
        self._version = 0
        self._local_forwards = 0  # Sebulba contract: stays 0 with a pool
        self._mailbox = None
        if job and inference is None:
            from ray_tpu.rllib.podracer.weights import WeightMailbox

            self._mailbox = WeightMailbox(job)
        # episode-return bookkeeping (reference: metrics on the EnvRunner)
        self._ep_return = np.zeros(num_envs, np.float32)
        self._recent_returns: collections.deque = collections.deque(maxlen=100)
        self._lifetime_steps = 0

        self._explore = jax.jit(self.module.forward_exploration)
        self._value = jax.jit(self.module.value)

    def set_weights(self, params, version: int = 0) -> None:
        self.params = params
        self._version = int(version)

    # ------------------------------------------------------------ policy
    def _poll_weights(self) -> None:
        if self._mailbox is not None:
            v, params = self._mailbox.poll()
            if params is not None:
                self.params, self._version = params, v

    def _pool_act(self, obs, sub):
        import ray_tpu

        actions, logp, values, version = ray_tpu.get(
            self._pool.act.remote(np.asarray(obs, np.float32),
                                  np.asarray(sub)), timeout=120)
        self._version = int(version)
        return actions, logp, values

    def _values_of(self, obs) -> np.ndarray:
        """Bootstrap values — pooled in Sebulba mode (the runner never
        touches the value net locally either)."""
        import jax

        if self._pool is not None:
            self._key, sub = jax.random.split(self._key)
            _, _, values = self._pool_act(obs, sub)
            return np.asarray(values)
        self._local_forwards += 1
        return np.asarray(self._value(self.params, obs))

    def _chaos_tick(self) -> None:
        from ray_tpu._private import fault_injection

        if fault_injection.ENABLED:
            action = fault_injection.hit(
                "rllib.sample", f"runner{self.runner_idx}")
            if action == "kill":
                fault_injection.kill_self()

    # ------------------------------------------------------------ sample
    def sample(self, weights=None) -> Dict[str, np.ndarray]:
        """One fragment of rollout_length steps across all K envs."""
        import jax

        self._chaos_tick()
        if weights is not None:
            self.params = weights
        elif self._mailbox is not None:
            # every fragment starts with a version check: one cheap KV
            # read; the weight payload only transfers on a version change
            self._poll_weights()
        if self._pool is None:
            assert self.params is not None, "set_weights before sample"
        T, K = self.rollout_length, self.num_envs
        out = {
            "obs": np.empty((T, K, self.env.observation_size), np.float32),
            "actions": np.empty((T, K), np.int32),
            "logp": np.empty((T, K), np.float32),
            "values": np.empty((T, K), np.float32),
            "rewards": np.empty((T, K), np.float32),
            "terminated": np.empty((T, K), bool),
            "truncated": np.empty((T, K), bool),
        }
        final_obs = np.empty((T, K, self.env.observation_size), np.float32)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            if self._pool is not None:
                actions, logp, values = self._pool_act(self.obs, sub)
            else:
                self._local_forwards += 1
                actions, logp, values = self._explore(
                    self.params, self.obs, sub)
            actions = np.asarray(actions)
            out["obs"][t] = self.obs
            out["actions"][t] = actions
            out["logp"][t] = np.asarray(logp)
            out["values"][t] = np.asarray(values)
            next_obs, rewards, terminated, truncated, info = \
                self.env.step(actions)
            out["rewards"][t] = rewards
            out["terminated"][t] = terminated
            out["truncated"][t] = truncated
            final_obs[t] = info["final_obs"]

            self._ep_return += rewards
            for i in np.nonzero(terminated | truncated)[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self.obs = next_obs
        self._lifetime_steps += T * K
        from ray_tpu.rllib._metrics import rllib_metrics

        rllib_metrics()["env_steps"].inc(
            T * K, {"job": self.job or "default"})

        # next_values[t] = V of the TRUE successor state: values[t+1] inside
        # an episode, V(obs after the fragment) at the tail, 0 at termination,
        # V(pre-reset final obs) at truncation (time-limit bootstrapping —
        # truncation is not failure, the episode just stopped being observed).
        tail_value = self._values_of(self.obs)
        next_values = np.concatenate(
            [out["values"][1:], tail_value[None]], axis=0)
        next_values[out["terminated"]] = 0.0
        if out["truncated"].any():
            # evaluate on the full fixed (T*K, obs) shape and index after:
            # a data-dependent batch (the truncation count) would recompile
            # the jit for every distinct count
            tr = np.nonzero(out["truncated"])
            v_final = self._values_of(
                final_obs.reshape(T * K, -1)).reshape(T, K)
            next_values[tr] = v_final[tr]
        out["next_values"] = next_values.astype(np.float32)
        return out

    # ----------------------------------------------------------- streaming
    def run_stream(self, num_fragments: int):
        """Continuous sample loop (declare ``num_returns="streaming"`` at
        the call site / via method meta): each yielded fragment is sealed
        into plasma immediately, and the weight mailbox is polled between
        fragments — the driver never relaunches per fragment and never
        ships weights as arguments."""
        for _ in range(int(num_fragments)):
            batch = self.sample()  # polls the weight mailbox itself
            yield {
                "batch": batch,
                "policy_version": int(self._version),
                "runner_idx": self.runner_idx,
                "episode_return_mean": (
                    float(np.mean(self._recent_returns))
                    if self._recent_returns else float("nan")),
                "num_episodes": len(self._recent_returns),
                "lifetime_steps": self._lifetime_steps,
            }

    run_stream.__ray_method_options__ = {"num_returns": "streaming"}

    def get_metrics(self) -> Dict:
        return {
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "num_episodes": len(self._recent_returns),
            "num_env_steps_sampled_lifetime": self._lifetime_steps,
        }

    def get_debug(self) -> Dict:
        return {"local_forwards": self._local_forwards,
                "policy_version": self._version,
                "lifetime_steps": self._lifetime_steps}

    def ping(self) -> bool:
        return True
