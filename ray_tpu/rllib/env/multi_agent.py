"""Multi-agent env surface: dict-keyed agents, per-agent policy mapping.

Counterpart of the reference's MultiAgentEnv (reference:
rllib/env/multi_agent_env.py — dict obs/action spaces keyed by agent id,
per-agent reward/terminated dicts with the ``__all__`` episode flag;
policy mapping via config.multi_agent(policies=...,
policy_mapping_fn=...), rllib/algorithms/algorithm_config.py multi_agent()).

TPU-first layout mirrors the single-agent split: the env + runner are host
numpy programs; each POLICY is a params pytree updated by its own jitted
learner.  The runner routes observations agent→policy with the mapping fn,
and emits one PPO-shaped time-major batch PER POLICY — agents sharing a
policy become extra env columns (K_policy = num_envs × agents_mapped), so
the single-agent learner update is reused unchanged.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional

import numpy as np


class MultiAgentVectorEnv:
    """Vectorized multi-agent env: K independent copies of an A-agent world.

    Episodes are SHARED per copy (the reference's ``__all__`` semantics):
    when a copy's episode ends, every agent in that copy resets together.
    Per-agent terminated/truncated dicts still differ — an agent that
    personally failed is terminated (no bootstrap), a surviving agent in an
    ending episode is truncated (bootstrap through the cut).
    """

    agents: List[str]
    observation_sizes: Dict[str, int]
    num_actions: Dict[str, int]

    def reset(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        """actions: {agent: (K,)}; returns (obs, rewards, terminated,
        truncated, info) — each a {agent: (K, ...)} dict; info["final_obs"]
        holds pre-reset observations (valid where an episode ended)."""
        raise NotImplementedError


class MultiCartPole(MultiAgentVectorEnv):
    """A-agent cartpole: each agent balances its own pole, but the EPISODE is
    shared — it ends when any pole falls (or at 500 steps), which gives the
    shared-fate termination structure real multi-agent envs have while the
    physics stays exactly CartPole-v1 (comparable returns)."""

    max_episode_steps = 500

    def __init__(self, num_envs: int, num_agents: int = 2, seed: int = 0):
        from ray_tpu.rllib.env.cartpole import CartPoleVectorEnv

        self.num_envs = num_envs
        self.agents = [f"agent_{i}" for i in range(num_agents)]
        self.observation_sizes = {a: 4 for a in self.agents}
        self.num_actions = {a: 2 for a in self.agents}
        self._pole = {a: CartPoleVectorEnv(num_envs, seed=seed + 131 * i)
                      for i, a in enumerate(self.agents)}
        self.steps = np.zeros(num_envs, np.int32)

    def reset(self) -> Dict[str, np.ndarray]:
        self.steps[:] = 0
        return {a: p.reset() for a, p in self._pole.items()}

    def step(self, actions: Dict[str, np.ndarray]):
        obs, rewards, fell, final = {}, {}, {}, {}
        for a in self.agents:
            pole = self._pole[a]
            # step WITHOUT auto-reset semantics: we manage shared episodes,
            # so suppress the per-pole step counter's own truncation
            pole.steps[:] = 0
            o, r, term, _trunc, info = pole.step(actions[a])
            obs[a] = o
            rewards[a] = r
            fell[a] = term
            final[a] = info["final_obs"]
        self.steps += 1
        any_fell = np.zeros(self.num_envs, bool)
        for a in self.agents:
            any_fell |= fell[a]
        timeout = self.steps >= self.max_episode_steps
        done = any_fell | timeout
        terminated = {a: fell[a] for a in self.agents}
        truncated = {a: done & ~fell[a] for a in self.agents}
        if done.any():
            # shared reset: every agent's copy restarts together.  The
            # sub-env's final_obs is already the pre-reset state for every
            # copy (fallen or not); here only the not-personally-fallen
            # agents of done copies still need their state re-sampled.
            for a in self.agents:
                pole = self._pole[a]
                fresh = pole._sample_state(int(done.sum()))
                pole.state[done] = fresh
                obs[a] = pole.state.copy()
            self.steps[done] = 0
        info = {"final_obs": final, "done": done}
        return obs, rewards, terminated, truncated, info


_MA_REGISTRY: Dict[str, Callable] = {}


def register_multi_agent_env(name: str, creator: Callable) -> None:
    """reference: tune.register_env with a MultiAgentEnv creator."""
    _MA_REGISTRY[name] = creator


def make_multi_agent_env(name: str, num_envs: int,
                         seed: int = 0) -> MultiAgentVectorEnv:
    if name not in _MA_REGISTRY:
        raise ValueError(f"unknown multi-agent env {name!r}; "
                         f"registered: {sorted(_MA_REGISTRY)}")
    return _MA_REGISTRY[name](num_envs=num_envs, seed=seed)


register_multi_agent_env(
    "MultiCartPole",
    lambda num_envs, seed=0: MultiCartPole(num_envs, num_agents=2, seed=seed))


class MultiAgentEnvRunner:
    """Samples PPO-shaped fragments per POLICY from a multi-agent env.

    reference: rllib/env/multi_agent_env_runner.py (sample keyed by module
    id).  Agents mapped to the same policy are concatenated as extra env
    columns, so each policy's batch is the exact (T, K', ...) layout the
    single-agent JaxLearner consumes — per-policy GAE included.
    """

    def __init__(self, env_name: str, num_envs: int, rollout_length: int,
                 policy_specs: Dict[str, Dict],
                 policy_mapping_fn: Callable[[str], str], seed: int = 0):
        import sys

        if "jax" in sys.modules:
            import jax._src.xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        else:
            initialized = False
        if not initialized:
            # pin rollout inference to CPU BEFORE the backend initializes
            # (see EnvRunner.__init__: un-pinned runners on a TPU VM
            # dispatch every per-step inference to the chip, ~270x slower)
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax

        from ray_tpu.rllib.core.rl_module import DiscretePolicyModule

        self.env = make_multi_agent_env(env_name, num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.policy_mapping_fn = policy_mapping_fn
        self.modules = {pid: DiscretePolicyModule(**spec)
                        for pid, spec in policy_specs.items()}
        self.params: Dict[str, object] = {}
        self._agent_policy = {a: policy_mapping_fn(a)
                              for a in self.env.agents}
        for a, pid in self._agent_policy.items():
            if pid not in self.modules:
                raise ValueError(
                    f"agent {a!r} maps to unknown policy {pid!r}")
        self._key = jax.random.PRNGKey(seed)
        self._explore = {pid: jax.jit(m.forward_exploration)
                         for pid, m in self.modules.items()}
        self._value = {pid: jax.jit(m.value)
                       for pid, m in self.modules.items()}
        self.obs = self.env.reset()
        self._ep_return = np.zeros(num_envs, np.float32)
        self._recent_returns: collections.deque = collections.deque(maxlen=100)
        self._lifetime_steps = 0

    def sample(self, weights: Optional[Dict[str, object]] = None
               ) -> Dict[str, Dict[str, np.ndarray]]:
        import jax

        if weights is not None:
            self.params = weights
        T, K = self.rollout_length, self.num_envs
        A = self.env.agents
        per_agent = {a: {
            "obs": np.empty((T, K, self.env.observation_sizes[a]), np.float32),
            "actions": np.empty((T, K), np.int32),
            "logp": np.empty((T, K), np.float32),
            "values": np.empty((T, K), np.float32),
            "rewards": np.empty((T, K), np.float32),
            "terminated": np.empty((T, K), bool),
            "truncated": np.empty((T, K), bool),
            "final_obs": np.empty((T, K, self.env.observation_sizes[a]),
                                  np.float32),
        } for a in A}
        for t in range(T):
            actions = {}
            for a in A:
                pid = self._agent_policy[a]
                self._key, sub = jax.random.split(self._key)
                acts, logp, values = self._explore[pid](
                    self.params[pid], self.obs[a], sub)
                actions[a] = np.asarray(acts)
                per_agent[a]["obs"][t] = self.obs[a]
                per_agent[a]["actions"][t] = actions[a]
                per_agent[a]["logp"][t] = np.asarray(logp)
                per_agent[a]["values"][t] = np.asarray(values)
            obs, rewards, terminated, truncated, info = self.env.step(actions)
            for a in A:
                per_agent[a]["rewards"][t] = rewards[a]
                per_agent[a]["terminated"][t] = terminated[a]
                per_agent[a]["truncated"][t] = truncated[a]
                per_agent[a]["final_obs"][t] = info["final_obs"][a]
                self._ep_return += rewards[a] / len(A)
            for i in np.nonzero(info["done"])[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self.obs = obs
        self._lifetime_steps += T * K  # env steps, not agent-steps

        # bootstrap per agent column, then group columns by policy
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for a in A:
            pid = self._agent_policy[a]
            b = per_agent[a]
            tail = np.asarray(self._value[pid](self.params[pid], self.obs[a]))
            nv = np.concatenate([b["values"][1:], tail[None]], axis=0)
            nv[b["terminated"]] = 0.0
            if b["truncated"].any():
                tr = np.nonzero(b["truncated"])
                vf = np.asarray(self._value[pid](
                    self.params[pid],
                    b["final_obs"].reshape(T * K, -1))).reshape(T, K)
                nv[tr] = vf[tr]
            b["next_values"] = nv.astype(np.float32)
            del b["final_obs"]
            grp = out.setdefault(pid, {})
            for k, v in b.items():
                grp.setdefault(k, []).append(v)
        return {pid: {k: np.concatenate(vs, axis=1)
                      for k, vs in grp.items()}
                for pid, grp in out.items()}

    def get_metrics(self) -> Dict:
        return {
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "num_episodes": len(self._recent_returns),
            "num_env_steps_sampled_lifetime": self._lifetime_steps,
        }

    def ping(self) -> bool:
        return True
