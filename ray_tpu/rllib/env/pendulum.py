"""Native vectorized Pendulum-v1 (no gym in the TPU image).

Standard underactuated pendulum swing-up (identical constants/reward to
Gymnasium's Pendulum-v1 so published SAC learning curves are comparable):
obs = (cos th, sin th, thdot), action = torque in [-2, 2],
reward = -(angle^2 + 0.1*thdot^2 + 0.001*a^2), truncation at 200 steps,
no termination.  Vectorized over K envs in numpy with auto-reset — env
stepping stays on the CPU actor (SURVEY §3.5: EnvRunners stay on CPU; the
Learner is the device program).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class PendulumVectorEnv:
    observation_size = 3
    action_size = 1
    max_action = 2.0
    max_episode_steps = 200
    continuous = True

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self.th = np.zeros(num_envs, np.float32)
        self.thdot = np.zeros(num_envs, np.float32)
        self.steps = np.zeros(num_envs, np.int32)
        self.reset()

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self.th), np.sin(self.th), self.thdot],
                        axis=1).astype(np.float32)

    def _sample(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        th = self._rng.uniform(-np.pi, np.pi, n).astype(np.float32)
        thdot = self._rng.uniform(-1.0, 1.0, n).astype(np.float32)
        return th, thdot

    def reset(self) -> np.ndarray:
        self.th, self.thdot = self._sample(self.num_envs)
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        """actions: (K,) or (K,1) torque.  Auto-resets truncated envs; the
        returned obs is the next episode's first obs at done slots, with
        info["final_obs"] carrying the true pre-reset observation."""
        a = np.clip(np.asarray(actions, np.float32).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th, thdot = self.th, self.thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        reward = -(norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * a ** 2)

        newthdot = thdot + (3.0 * self.G / (2.0 * self.L) * np.sin(th)
                            + 3.0 / (self.M * self.L ** 2) * a) * self.DT
        newthdot = np.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT
        self.th, self.thdot = newth.astype(np.float32), \
            newthdot.astype(np.float32)
        self.steps += 1

        terminated = np.zeros(self.num_envs, bool)
        truncated = self.steps >= self.max_episode_steps
        final_obs = self._obs()
        if truncated.any():
            n = int(truncated.sum())
            th_new, thdot_new = self._sample(n)
            self.th[truncated] = th_new
            self.thdot[truncated] = thdot_new
            self.steps[truncated] = 0
        return (self._obs(), reward.astype(np.float32), terminated,
                truncated, {"final_obs": final_obs})
