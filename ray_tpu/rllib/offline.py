"""Offline RL: dataset recording + BC / MARWIL training from logged episodes.

Counterpart of the reference's offline stack (reference: rllib/offline/ —
dataset readers feeding Learners; rllib/algorithms/marwil/marwil.py MARWIL
with BC as its beta=0 special case, rllib/algorithms/bc/bc.py).  TPU-first
shape: episodes are recorded through ``ray_tpu.data`` (JSON blocks), the
whole dataset lives in device memory as dense arrays, and each training
iteration is ONE jitted scan over minibatches — no per-row Python.

MARWIL loss (Wang et al. 2018, exponentially weighted imitation):

    L = -E[ exp(beta * A / c) * log pi(a|s) ] + vf_coef * E[(V(s) - R)^2]

with A = R - V(s) (advantage against the learned value baseline), c a
running norm of |A|, and R the dataset's discounted return-to-go.
beta = 0 recovers plain behavior cloning (the value head still trains, but
the policy term ignores it).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.rl_module import DiscretePolicyModule


# ---------------------------------------------------------------- recording

def record_dataset(path: str, env_name: str, n_episodes: int,
                   policy_fn: Optional[Callable] = None, seed: int = 0,
                   gamma: float = 0.99) -> Dict[str, Any]:
    """Roll out ``policy_fn(obs) -> actions`` (default: a decent CartPole
    heuristic so the data carries signal) and write one JSON row per step:
    ``{"obs", "action", "return_to_go"}`` (reference: offline output_config
    JSON episode writers).  Returns summary stats."""
    from ray_tpu import data as rt_data
    from ray_tpu.rllib.env import make_vector_env

    env = make_vector_env(env_name, 1, seed=seed)

    if policy_fn is None:
        def policy_fn(obs):  # lean-direction heuristic, ~mean return 40+
            return (obs[:, 2] + 0.5 * obs[:, 3] > 0).astype(np.int64)

    rows = []
    returns = []
    for _ in range(n_episodes):
        obs = env.reset()
        ep: list = []
        while True:
            a = policy_fn(obs)
            nxt, r, term, trunc, info = env.step(a)
            ep.append((obs[0].tolist(), int(a[0]), float(r[0])))
            obs = nxt
            if bool(term[0] or trunc[0]):
                break
        # discounted return-to-go per step
        g = 0.0
        rtg = [0.0] * len(ep)
        for i in range(len(ep) - 1, -1, -1):
            g = ep[i][2] + gamma * g
            rtg[i] = g
        returns.append(sum(r for _, _, r in ep))
        rows.extend({"obs": o, "action": a, "return_to_go": rt}
                    for (o, a, _), rt in zip(ep, rtg))
    rt_data.from_items(rows).write_json(path)
    return {"episodes": n_episodes, "steps": len(rows),
            "mean_return": float(np.mean(returns))}


# ----------------------------------------------------------------- learning

def _marwil_update(module, tx, params, opt_state, norm, batch, *,
                   beta: float, vf_coef: float, minibatch: int):
    import jax
    import jax.numpy as jnp

    n = batch["obs"].shape[0]
    n_mb = max(n // minibatch, 1)
    usable = n_mb * minibatch
    mbs = {k: v[:usable].reshape((n_mb, minibatch) + v.shape[1:])
           for k, v in batch.items()}

    def loss_fn(p, norm, mb):
        logp, _ent = module.logp_entropy(p, mb["obs"], mb["action"])
        v = module.value(p, mb["obs"])
        adv = mb["return_to_go"] - v
        # running norm of |A| keeps exp() in range (reference: MARWIL's
        # moving average of the squared advantage)
        norm_new = 0.99 * norm + 0.01 * jnp.mean(jnp.abs(
            jax.lax.stop_gradient(adv)))
        w = jnp.exp(jnp.clip(
            beta * jax.lax.stop_gradient(adv) / jnp.maximum(norm_new, 1e-3),
            -10.0, 10.0))
        pi_loss = -jnp.mean(w * logp)
        vf_loss = jnp.mean(adv ** 2)
        return pi_loss + vf_coef * vf_loss, (norm_new, pi_loss, vf_loss)

    def body(carry, mb):
        params, opt_state, norm = carry
        (_, (norm, pi_l, vf_l)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, norm, mb)
        import optax

        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, norm), (pi_l, vf_l)

    (params, opt_state, norm), (pi_ls, vf_ls) = jax.lax.scan(
        body, (params, opt_state, norm), mbs)
    return params, opt_state, norm, jnp.mean(pi_ls), jnp.mean(vf_ls)


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.input_path: Optional[str] = None
        self.training_params = {
            "lr": 3e-4,
            "beta": 1.0,
            "vf_coef": 1.0,
            "grad_clip": 10.0,
            "train_batch_size": 2048,
            "minibatch_size": 256,
        }

    def offline_data(self, *, input_path: str) -> "MARWILConfig":
        """Where the logged episodes live (reference:
        AlgorithmConfig.offline_data(input_))."""
        self.input_path = input_path
        return self

    @property
    def algo_class(self):
        return MARWIL


class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (reference: bc.py subclasses
    MARWIL the same way)."""

    def __init__(self):
        super().__init__()
        self.training_params["beta"] = 0.0

    @property
    def algo_class(self):
        return BC


class MARWIL(Algorithm):
    def setup(self, config: MARWILConfig) -> None:
        import jax
        import optax

        from ray_tpu import data as rt_data
        from ray_tpu.rllib.algorithms.algorithm import build_module_spec

        if config.learner_platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        if not config.input_path:
            raise ValueError("offline algorithms need "
                             "config.offline_data(input_path=...)")
        spec = build_module_spec(config)
        p = config.training_params
        self.module = DiscretePolicyModule(
            observation_size=spec["observation_size"],
            num_actions=spec["num_actions"], hidden=spec["hidden"])
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.tx = optax.chain(optax.clip_by_global_norm(p["grad_clip"]),
                              optax.adam(p["lr"]))
        self.opt_state = self.tx.init(self.params)
        self._norm = jax.numpy.asarray(1.0)
        self._update = jax.jit(functools.partial(
            _marwil_update, self.module, self.tx, beta=p["beta"],
            vf_coef=p["vf_coef"], minibatch=p["minibatch_size"]))

        # the dataset rides ray_tpu.data; dense arrays once, then jit-only
        rows = rt_data.read_json(config.input_path).take_all()
        self._obs = np.asarray([r["obs"] for r in rows], np.float32)
        self._actions = np.asarray([r["action"] for r in rows], np.int64)
        self._rtg = np.asarray([r["return_to_go"] for r in rows], np.float32)
        self._rng = np.random.default_rng(config.seed)
        self._eval_env = None

    def training_step(self) -> Dict[str, Any]:
        p = self.config.training_params
        idx = self._rng.integers(0, len(self._obs),
                                 p["train_batch_size"])
        batch = {"obs": self._obs[idx], "action": self._actions[idx],
                 "return_to_go": self._rtg[idx]}
        self.params, self.opt_state, self._norm, pi_l, vf_l = self._update(
            self.params, self.opt_state, self._norm, batch)
        return {"policy_loss": float(pi_l), "vf_loss": float(vf_l),
                "dataset_size": len(self._obs)}

    def evaluate(self, n_episodes: int = 10) -> Dict[str, float]:
        """Greedy rollouts of the learned policy (reference:
        Algorithm.evaluate)."""
        import jax.numpy as jnp

        from ray_tpu.rllib.env import make_vector_env

        if self._eval_env is None:
            self._eval_env = make_vector_env(self.config.env, 1,
                                             seed=self.config.seed + 7)
        env = self._eval_env
        returns = []
        for _ in range(n_episodes):
            obs = env.reset()
            total = 0.0
            while True:
                a = np.asarray(self.module.forward_inference(
                    self.params, jnp.asarray(obs)))
                obs, r, term, trunc, _ = env.step(a)
                total += float(r[0])
                if bool(term[0] or trunc[0]):
                    break
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}


class BC(MARWIL):
    pass
