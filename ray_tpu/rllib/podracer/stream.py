"""FragmentStream: driver-side multiplexer over streaming env-runner gangs.

Each runner executes a continuous ``run_stream(num_fragments)`` sample loop
declared ``num_returns="streaming"``: every trajectory fragment is sealed
into plasma the moment the runner yields it, and the driver's speculative
per-item refs become waitable right then — no per-fragment actor round
trip, no driver relaunch between fragments.  The multiplexer waits on
(item, primary) pairs across ALL runners at once, hands out whichever
fragments are ready, and relaunches a runner's next streaming call when the
previous one drains — so a runner is never idle for more than one
driver-notice latency, and the number of unconsumed fragments per runner is
bounded by ``fragments_per_call`` (+ one draining call's tail): that bound
is the stream's backpressure.

A dead runner (SIGKILL mid-stream) surfaces on the primary ref of its
in-flight call: the consumer opens an ``rllib`` incident (detect ->
rebuild -> restore -> resume, emitting ``recovery_seconds{subsystem=rllib}``
on close), respawns the runner via the caller's factory, and keeps
consuming the surviving streams throughout — fragments the victim sealed
before dying were already consumed; the unsealed remainder is simply lost
(V-trace never sees it).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Tuple

from ray_tpu.exceptions import (ObjectLostError, OwnerDiedError,
                                RayActorError, WorkerCrashedError)

_DEATH_ERRORS = (RayActorError, WorkerCrashedError, ObjectLostError,
                 OwnerDiedError)


class _Cursor:
    __slots__ = ("idx", "runner", "gen", "i", "dead")

    def __init__(self, idx: int, runner):
        self.idx = idx
        self.runner = runner
        self.gen = None
        self.i = 0  # next unconsumed item index within the current call
        self.dead = False


class FragmentStream:
    """Multiplex ``runners``' streaming sample loops into one driver-side
    fragment iterator.

    ``respawn(idx) -> handle`` (optional) replaces a dead runner; without
    it a dead stream is dropped (and the stream raises once ALL are dead).
    """

    def __init__(self, runners: List[Any], *, fragments_per_call: int = 8,
                 timeout_s: float = 300.0,
                 respawn: Optional[Callable[[int], Any]] = None,
                 job: str = "default"):
        self.job = job
        self._fragments_per_call = max(int(fragments_per_call), 1)
        self._timeout_s = timeout_s
        self._respawn = respawn
        self._cursors = [_Cursor(i, r) for i, r in enumerate(runners)]
        for c in self._cursors:
            self._launch(c)

    # ------------------------------------------------------------- launch
    def _launch(self, c: _Cursor) -> None:
        c.gen = c.runner.run_stream.remote(self._fragments_per_call)
        c.i = 0

    @property
    def runners(self) -> List[Any]:
        return [c.runner for c in self._cursors]

    def alive(self) -> int:
        return sum(1 for c in self._cursors if not c.dead)

    # ------------------------------------------------------------ consume
    def next_fragments(self, timeout_s: Optional[float] = None
                       ) -> List[Tuple[int, Any, dict]]:
        """Block until at least one fragment is ready; return every ready
        fragment as ``(runner_idx, fragment_ref, fragment)`` — the ref is
        the fragment's existing plasma residence, so forwarding it to a
        learner actor costs no re-put."""
        import ray_tpu
        from ray_tpu.rllib._metrics import rllib_metrics

        budget = self._timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + budget
        out: List[Tuple[int, Any, dict]] = []
        while not out:
            if not any(not c.dead for c in self._cursors):
                raise RuntimeError(
                    "every env-runner stream is dead and no respawn "
                    "factory was provided")
            watch, owner = [], {}
            for c in self._cursors:
                if c.dead:
                    continue
                spec = c.gen.item_ref(c.i)
                prim = c.gen._primary
                watch.append(spec)
                owner[id(spec)] = (c, "item", c.gen)
                watch.append(prim)
                owner[id(prim)] = (c, "prim", c.gen)
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise TimeoutError(
                    f"no env-runner produced a fragment in {budget}s")
            ready, _ = ray_tpu.wait(watch, num_returns=1, timeout=rem)
            if not ready:
                continue
            # scoop everything else already done — one pass hands out every
            # ready fragment across all runners, no per-runner serialization
            more, _ = ray_tpu.wait(watch, num_returns=len(watch), timeout=0)
            for ref in {id(r): r for r in ready + more}.values():
                c, kind, gen = owner[id(ref)]
                if c.dead or c.gen is not gen:
                    continue  # cursor respawned/relaunched this pass
                if kind == "item":
                    out.append((c.idx, ref, ray_tpu.get(ref)))
                    c.i += 1
                    continue
                # primary done: the call finished (drain the tail and
                # relaunch) or the runner died (incident + respawn)
                try:
                    refs = gen.completed()
                except _DEATH_ERRORS:
                    self._on_death(c)
                    continue
                for r in refs[c.i:]:
                    out.append((c.idx, r, ray_tpu.get(r)))
                self._launch(c)
        if out:
            rllib_metrics()["fragments"].inc(len(out), {"job": self.job})
        return out

    # -------------------------------------------------------------- death
    def _on_death(self, c: _Cursor) -> None:
        from ray_tpu._private import incidents
        from ray_tpu.rllib._metrics import rllib_metrics

        inc = incidents.open_incident(
            "rllib", kind="env_runner_death", detail=f"runner{c.idx}")
        inc.stamp("detect")
        if self._respawn is None:
            c.dead = True
            inc.close(ok=False)
            return
        c.runner = self._respawn(c.idx)
        inc.stamp("rebuild")
        self._launch(c)
        inc.stamp("restore")
        inc.close()
        rllib_metrics()["runner_restarts"].inc(1, {"job": self.job})
