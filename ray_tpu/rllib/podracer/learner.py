"""Podracer learner gang: per-learner jitted V-trace, collective grad fold.

Each learner holds a full replica of the policy (same seed => identical
init on every rank) and runs the IMPALA V-trace update in two jitted
halves: ``grads`` (loss + gradient) and ``apply`` (optimizer step).
Between them the gradient pytree is raveled into one flat vector and
folded through the gang's persistent collective group with
``allreduce_async(op="mean")`` — optionally with ``quorum=K-1`` so one
straggling learner never stalls a round (its late gradient parks at the
root and folds into the next fold; arXiv:2505.23523).  Because every rank
applies the SAME folded gradient to the SAME replica, parameters stay
bitwise identical across the gang and rank 0 alone publishes versioned
weights to the :class:`~ray_tpu.rllib.podracer.weights.WeightMailbox`.

``world_size=1`` skips the group entirely, so a driver-local learner and a
one-actor gang execute the identical jit programs — that is the bitwise
Anakin/Sebulba parity contract the tests pin down.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _vtrace_grads(module, params, batch, *, gamma, rho_clip, c_clip,
                  vf_loss_coeff, entropy_coeff):
    """Loss + gradient half of the IMPALA update (same math as the fused
    single-learner update this package replaced; ops/vtrace.py)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.vtrace import vtrace_from_fragments

    T, K = batch["rewards"].shape
    obs = batch["obs"].reshape(T * K, -1)
    actions = batch["actions"].reshape(T * K)
    dones = batch["terminated"] | batch["truncated"]

    def loss_fn(p):
        # target policy/value under CURRENT params; behavior logp/values in
        # the batch came from the stale runner weights
        logp, entropy = module.logp_entropy(p, obs, actions)
        v = module.value(p, obs)
        logp_t = logp.reshape(T, K)
        v_t = v.reshape(T, K)
        nv = jnp.concatenate([v_t[1:], batch["next_values"][-1:]], axis=0)
        nv = jnp.where(dones, batch["next_values"], nv)
        vs, pg_adv = vtrace_from_fragments(
            batch["logp"], jax.lax.stop_gradient(logp_t),
            batch["rewards"], jax.lax.stop_gradient(v_t),
            jax.lax.stop_gradient(nv), dones, gamma, rho_clip, c_clip)
        pg_loss = -(jax.lax.stop_gradient(pg_adv) * logp_t).mean()
        vf_loss = 0.5 * ((v_t - jax.lax.stop_gradient(vs)) ** 2).mean()
        loss = (pg_loss + vf_loss_coeff * vf_loss
                - entropy_coeff * entropy.mean())
        return loss, {
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy.mean(),
            "mean_vtrace_target": vs.mean(),
            "mean_is_ratio": jnp.exp(
                jax.lax.stop_gradient(logp_t) - batch["logp"]).mean(),
        }

    (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    stats["total_loss"] = loss
    return stats, grads


def _apply_grads(tx, params, opt_state, grads):
    import optax

    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state


def named_parameters(params) -> List[str]:
    """Stable, stage-count-independent names for every param leaf (e.g.
    ``pi/0/w``) — the same naming contract
    ``train/pipeline/partition.py`` keeps across pipeline splits, so a big
    policy trained under ``JaxTrainer(pipeline_stages=..., mesh=...)``
    checkpoints and republishes into the mailbox without a rename pass."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
            for path, _ in leaves]


class PodracerLearner:
    """One learner replica (driver-local object or actor — same class)."""

    def __init__(self, module_spec: Dict, training_params: Dict, *,
                 seed: int = 0, rank: int = 0, world_size: int = 1,
                 job: str = "", quorum: Optional[int] = None,
                 platform: Optional[str] = None, publish_every: int = 1,
                 collective_timeout_s: float = 120.0):
        if platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax
        import optax

        from ray_tpu.rllib.core.rl_module import DiscretePolicyModule

        self.module = DiscretePolicyModule(**module_spec)
        self.config = dict(training_params)
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 40.0)),
            optax.adam(self.config.get("lr", 5e-4)),
        )
        self.opt_state = self.tx.init(self.params)
        self.rank = rank
        self.world_size = world_size
        self.job = job
        self._quorum = quorum
        self._publish_every = max(int(publish_every), 1)
        self._timeout_s = collective_timeout_s
        self._group = None
        self._updates = 0
        self._version = 0
        self._mailbox = None
        if job and rank == 0:
            from ray_tpu.rllib.podracer.weights import WeightMailbox

            # keep=4: a runner mid-fetch loses the race only if four
            # versions roll out during its one object-store get
            self._mailbox = WeightMailbox(job, keep=4)
        self._grads = jax.jit(functools.partial(
            _vtrace_grads, self.module,
            gamma=self.config.get("gamma", 0.99),
            rho_clip=self.config.get("rho_clip", 1.0),
            c_clip=self.config.get("c_clip", 1.0),
            vf_loss_coeff=self.config.get("vf_loss_coeff", 0.5),
            entropy_coeff=self.config.get("entropy_coeff", 0.01),
        ))
        self._apply = jax.jit(functools.partial(_apply_grads, self.tx))

    # ----------------------------------------------------------- grad fold
    def _ensure_group(self):
        if self._group is None and self.world_size > 1:
            from ray_tpu.util.collective.collective import \
                get_or_init_collective_group

            self._group = get_or_init_collective_group(
                self.world_size, self.rank,
                group_name=f"rllib/{self.job or 'default'}/learners")
        return self._group

    def update(self, fragment) -> Dict[str, Any]:
        """One V-trace update; with a gang, folds this rank's gradient with
        the others' (mean) before applying.  Accepts either a raw batch
        dict or a streamed fragment wrapper carrying ``{"batch": ...}``."""
        from ray_tpu.rllib._metrics import rllib_metrics

        batch = fragment.get("batch", fragment) \
            if isinstance(fragment, dict) else fragment
        mlabels = {"job": self.job or "default"}
        t0 = time.monotonic()
        stats, grads = self._grads(self.params, batch)
        group = self._ensure_group()
        if group is not None:
            from jax.flatten_util import ravel_pytree

            flat, unravel = ravel_pytree(grads)
            handle = group.allreduce_async(
                np.asarray(flat), op="mean", quorum=self._quorum,
                timeout_s=self._timeout_s)
            folded = handle.wait(self._timeout_s)
            rllib_metrics()["allreduce_seconds"].observe(
                handle.op_seconds, mlabels)
            grads = unravel(folded)
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, grads)
        self._updates += 1
        out = {k: float(v) for k, v in stats.items()}
        if self._mailbox is not None and \
                self._updates % self._publish_every == 0:
            self._version = self._mailbox.publish(self.params)
        out["weight_version"] = float(self._version)
        rllib_metrics()["update_seconds"].observe(
            time.monotonic() - t0, mlabels)
        return out

    # ------------------------------------------------------------ weights
    def publish(self) -> int:
        """Publish the current params (v0 before any update, or an
        off-cycle refresh).  Rank 0 only."""
        if self._mailbox is None:
            raise RuntimeError("only rank 0 of a named job publishes")
        self._version = self._mailbox.publish(self.params)
        return self._version

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> None:
        self.params = params

    def get_version(self) -> int:
        return self._version

    def param_names(self) -> List[str]:
        return named_parameters(self.params)

    def nap(self, seconds: float) -> bool:
        """Occupy this learner's serial call queue for ``seconds`` — a
        deterministic straggler for quorum tests and benches."""
        time.sleep(float(seconds))
        return True

    def ping(self) -> bool:
        return True


class LearnerGang:
    """Driver-side handle over K PodracerLearner actors.

    Fragments buffer until one is available per rank, then the round
    dispatches to all ranks at once (the collective fold needs every rank
    in every op).  With ``quorum=K-1`` the round's stats return after K-1
    learners finish — the straggler's update keeps running and its result
    is harvested opportunistically on a later round.
    """

    def __init__(self, module_spec: Dict, training_params: Dict, *,
                 num_learners: int, job: str, seed: int = 0,
                 quorum: Optional[int] = None,
                 platform: Optional[str] = None, publish_every: int = 1,
                 round_timeout_s: float = 300.0):
        import ray_tpu

        if num_learners < 1:
            raise ValueError("LearnerGang needs num_learners >= 1")
        cls = ray_tpu.remote(PodracerLearner)
        self._learners = [
            cls.options(num_cpus=1).remote(
                module_spec, training_params, seed=seed, rank=r,
                world_size=num_learners, job=job, quorum=quorum,
                platform=platform, publish_every=publish_every)
            for r in range(num_learners)
        ]
        self._await_n = quorum if quorum is not None else num_learners
        self._timeout_s = round_timeout_s
        self._buf: List[Any] = []
        self._straggling: List[Any] = []

    def __len__(self) -> int:
        return len(self._learners)

    @property
    def learners(self) -> List[Any]:
        return list(self._learners)

    def submit(self, fragment_ref) -> List[Dict[str, Any]]:
        """Queue one fragment (pass the plasma REF, not the value — the
        learner fetches it without a driver re-put).  Returns the stats
        dicts of every update that completed as a result (empty until a
        full round dispatches)."""
        import ray_tpu

        self._buf.append(fragment_ref)
        k = len(self._learners)
        if len(self._buf) < k:
            return []
        round_frags, self._buf = self._buf[:k], self._buf[k:]
        refs = [ln.update.remote(f)
                for ln, f in zip(self._learners, round_frags)]
        ready, late = ray_tpu.wait(refs, num_returns=self._await_n,
                                   timeout=self._timeout_s)
        if len(ready) < self._await_n:
            raise TimeoutError(
                f"learner round: {len(ready)}/{self._await_n} updates "
                f"finished within {self._timeout_s}s")
        self._straggling.extend(late)
        done, self._straggling = ray_tpu.wait(
            self._straggling, num_returns=len(self._straggling), timeout=0)
        return ray_tpu.get(ready) + ray_tpu.get(done)

    def flush(self, timeout_s: float = 120.0) -> List[Dict[str, Any]]:
        """Collect every straggling update (end of run / test barrier)."""
        import ray_tpu

        done, self._straggling = ray_tpu.wait(
            self._straggling, num_returns=len(self._straggling),
            timeout=timeout_s)
        return ray_tpu.get(done)

    def publish(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._learners[0].publish.remote(), timeout=60)

    def get_weights(self, rank: int = 0):
        import ray_tpu

        return ray_tpu.get(self._learners[rank].get_weights.remote(),
                           timeout=60)

    def stop(self) -> None:
        import ray_tpu

        for ln in self._learners:
            try:
                ray_tpu.kill(ln)
            except Exception:
                pass
        self._learners = []
