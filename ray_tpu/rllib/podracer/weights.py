"""Versioned weight mailbox: one put, N gets, discovery via the GCS KV.

The relaunch-style IMPALA driver re-put the full weight pytree and shipped
the ref as an argument of EVERY sample call.  The mailbox inverts that:
the publisher puts each new version to the object store ONCE and records a
tiny ``(version, object id, owner address)`` tuple in the GCS KV; any
number of runners / inference pools poll the KV between fragments (a few
hundred bytes per poll) and fetch the payload only when the version
actually advanced.  The publisher pins the last ``keep`` version refs so a
subscriber that polled version v still resolves it while v+1 rolls out.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef

_NS = "podracer"


class WeightMailbox:
    """Publisher + subscriber handle for one job's versioned weights.

    Any process may construct one from the job name alone; ``publish`` is
    called by whoever owns the canonical params (the driver-local learner
    or the rank-0 learner actor), ``poll``/``peek`` by everyone else.
    """

    def __init__(self, job: str, keep: int = 2):
        if not job:
            raise ValueError("WeightMailbox needs a nonempty job name")
        self.job = job
        self.keep = max(int(keep), 1)
        self._key = f"{job}/weights"
        self._pinned: dict = {}  # version -> ObjectRef (publisher side)
        self._pub_version = 0
        self._sub_version = 0

    # ---------------------------------------------------------- publisher
    def publish(self, params: Any) -> int:
        """Put ``params`` once, advance the version, record it in the KV.
        Returns the new version number."""
        import ray_tpu
        from ray_tpu.rllib._metrics import rllib_metrics

        core = worker_mod.require_core()
        ref = ray_tpu.put(params)
        self._pub_version += 1
        v = self._pub_version
        self._pinned[v] = ref
        for old in [k for k in self._pinned if k <= v - self.keep]:
            del self._pinned[old]
        core.gcs_call_sync("kv_put", {
            "ns": _NS, "key": self._key,
            "value": (v, ref.binary(), ref.owner_addr(),
                      ref.owner_worker_id()),
        })
        rllib_metrics()["weight_version"].set(v, {"job": self.job})
        return v

    # --------------------------------------------------------- subscriber
    def _kv_record(self) -> Optional[tuple]:
        core = worker_mod.require_core()
        return core.gcs_call_sync("kv_get", {"ns": _NS, "key": self._key})

    def peek(self) -> int:
        """Latest published version (0 if nothing published yet) without
        fetching the payload."""
        rec = self._kv_record()
        return int(rec[0]) if rec else 0

    def poll(self, timeout: float = 10.0) -> Tuple[int, Optional[Any]]:
        """``(version, params)`` when a version newer than the last poll
        exists, else ``(last_seen_version, None)``.  One KV read; the
        object-store get happens only on a version change."""
        from ray_tpu.exceptions import GetTimeoutError, OwnerDiedError

        rec = self._kv_record()
        if not rec:
            return self._sub_version, None
        version, oid_b, owner_addr, owner_wid = rec
        version = int(version)
        if version <= self._sub_version:
            return self._sub_version, None
        # Reconstruct the publisher's ref from its wire identity (the same
        # triple __reduce__ ships); the publisher's pin of the last `keep`
        # versions keeps the object alive across the fetch window.
        ref = ObjectRef(ObjectID(oid_b),
                        tuple(owner_addr) if owner_addr else None, owner_wid)
        try:
            params = worker_mod.get(ref, timeout=timeout)
        except (GetTimeoutError, OwnerDiedError):
            # Lost the race: the publisher advanced past its pin window (or
            # died) while this fetch was in flight and version `version` was
            # freed from plasma.  Stale weights are the norm in an async
            # sampler — report "no update" and let the next poll read the
            # KV record that superseded this one.
            return self._sub_version, None
        self._sub_version = version
        return version, params

    @property
    def version(self) -> int:
        """Publisher: last published; subscriber: last successfully polled."""
        return self._pub_version or self._sub_version

    def clear(self) -> None:
        """Drop the KV record and the publisher's pins (job teardown)."""
        core = worker_mod.require_core()
        try:
            core.gcs_call_sync("kv_del", {"ns": _NS, "key": self._key})
        except Exception:
            pass
        self._pinned.clear()
