"""Podracer RL architectures on the actor runtime.

Counterpart of the Podracer paper's two TPU topologies (reference:
arXiv:2104.06272 — *Anakin*: rollout and learning co-located; *Sebulba*:
env-stepping actors decoupled from a central batched-inference tier and a
collective-backed learner gang):

- :mod:`.weights` — versioned weight mailbox: ONE object-store put per
  published version, N runner gets, discovery via a tiny GCS KV record
  (replaces re-shipping full weights as an argument of every sample call);
- :mod:`.stream` — driver-side multiplexer over env-runner actors running
  continuous ``num_returns="streaming"`` sample loops; fragments are
  consumed the moment each runner seals them, a dead runner surfaces as an
  incident (detect -> rebuild -> restore -> resume) and is respawned
  without stalling the surviving streams;
- :mod:`.learner` — per-learner jitted V-trace update with gradients
  folded through a persistent collective group (async allreduce, optional
  ``quorum=K-1`` straggler folding), rank 0 publishing versioned weights;
- :mod:`.inference` — the Sebulba split: an async InferencePool actor
  batches concurrent ``act()`` calls from many runners into single
  forwards (iteration-level batching, the llm/scheduler.py idea applied to
  policy inference); LLM policies route through ``llm_deployment()`` so
  trajectory prompts share the radix prefix cache.
"""

from ray_tpu.rllib.podracer.inference import (InferencePool,
                                              create_inference_pool,
                                              llm_policy_pool)
from ray_tpu.rllib.podracer.learner import LearnerGang, PodracerLearner
from ray_tpu.rllib.podracer.stream import FragmentStream
from ray_tpu.rllib.podracer.weights import WeightMailbox

__all__ = ["FragmentStream", "InferencePool", "LearnerGang",
           "PodracerLearner", "WeightMailbox", "create_inference_pool",
           "llm_policy_pool"]
