"""Sebulba batched-inference tier: rollout actors do ZERO local forwards.

An :class:`InferencePool` is an async actor that serves ``act(obs, key)``
for many env-runners at once.  Requests that arrive within one batching
window are folded into a SINGLE jitted forward over the concatenated
observations (iteration-level batching — the continuous-batching idea from
``llm/scheduler.py`` applied to policy inference), then each request's
actions are sampled from its own slice of the logits with its own PRNG
key, so pooled sampling is distributed exactly like runner-local sampling
would have been.  The pool owns the policy params: it polls the job's
:class:`~ray_tpu.rllib.podracer.weights.WeightMailbox` between iterations
and stamps every response with the version it used, which is what makes
the fragments' ``policy_version`` (and the staleness histogram) honest in
Sebulba mode.

LLM policies don't re-implement any of this: :func:`llm_policy_pool`
routes them through ``llm_deployment()``, whose engine already does
iteration-level batching AND caches shared trajectory prefixes in the
radix prefix cache (every env step re-sends the episode-so-far prompt;
consecutive steps hit the cache for all but the newest tokens).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class InferencePool:
    """Async batched-inference actor (create via
    :func:`create_inference_pool` so ``max_concurrency`` is set — a serial
    actor would deadlock waiting for batch-mates that can never arrive).

    The jitted forward compiles once per distinct total row count; with
    uniform per-runner env counts that is at most one program per distinct
    batch occupancy, bounded by the runner count.
    """

    def __init__(self, module_spec: Dict, *, job: str = "",
                 batch_window_s: float = 0.002, max_batch: int = 64,
                 weight_poll_every: int = 1):
        import sys

        if "jax" not in sys.modules:
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)  # inference pool is a host program
        import jax

        from ray_tpu.rllib.core.rl_module import DiscretePolicyModule

        self.module = DiscretePolicyModule(**module_spec)
        self.params = None
        self.job = job
        self._version = 0
        self._mailbox = None
        if job:
            from ray_tpu.rllib.podracer.weights import WeightMailbox

            self._mailbox = WeightMailbox(job)
        self._batch_window_s = batch_window_s
        self._max_batch = max_batch
        self._weight_poll_every = max(int(weight_poll_every), 1)
        self._pending: list = []  # (obs, key, future)
        self._wake = None
        self._loop_task = None
        self._iterations = 0
        self._requests = 0
        self._max_occupancy = 0

        def _fwd(params, obs):
            return self.module.logits(params, obs), \
                self.module.value(params, obs)

        self._fwd = jax.jit(_fwd)

    # ------------------------------------------------------------ weights
    def set_weights(self, params, version: int = 0) -> None:
        self.params = params
        self._version = int(version)

    async def _poll_weights(self) -> None:
        # async actor methods run ON the core worker's io loop: the
        # mailbox's blocking KV read + object get must hop to an executor
        # thread or they'd deadlock the very loop that resolves them
        if self._mailbox is not None and \
                self._iterations % self._weight_poll_every == 0:
            import asyncio

            v, params = await asyncio.get_event_loop().run_in_executor(
                None, self._mailbox.poll)
            if params is not None:
                self.params, self._version = params, v

    # ---------------------------------------------------------- serving
    async def act(self, obs, key) -> tuple:
        """Sample actions for one runner's observation batch; returns
        ``(actions, logp, values, policy_version)`` as numpy arrays.  The
        caller supplies the PRNG key (its own split sequence), so which
        pool iteration served the request never changes the sample."""
        import asyncio

        if self._wake is None:
            self._wake = asyncio.Event()
            self._loop_task = asyncio.get_event_loop().create_task(
                self._batch_loop())
        fut = asyncio.get_event_loop().create_future()
        self._pending.append((obs, key, fut))
        self._wake.set()
        return await fut

    async def _batch_loop(self) -> None:
        import asyncio

        import jax
        import numpy as np

        from ray_tpu.rllib._metrics import rllib_metrics

        labels = {"job": self.job or "default"}
        while True:
            await self._wake.wait()
            self._wake.clear()
            if not self._pending:
                continue
            # the window is what turns concurrent callers into occupancy:
            # the first arrival opens it, everyone landing inside folds in
            await asyncio.sleep(self._batch_window_s)
            batch, self._pending = (self._pending[:self._max_batch],
                                    self._pending[self._max_batch:])
            if self._pending:
                self._wake.set()  # overflow: next iteration takes the rest
            await self._poll_weights()
            self._iterations += 1
            self._requests += len(batch)
            self._max_occupancy = max(self._max_occupancy, len(batch))
            m = rllib_metrics()
            m["infer_batch"].observe(len(batch), labels)
            m["infer_requests"].inc(len(batch), labels)
            obs_cat = np.concatenate(
                [np.asarray(o, np.float32) for o, _, _ in batch], axis=0)
            logits, values = self._fwd(self.params, obs_cat)
            logp_all = jax.nn.log_softmax(logits)
            off = 0
            for obs, key, fut in batch:
                n = len(obs)
                sl = slice(off, off + n)
                off += n
                actions = jax.random.categorical(
                    jax.numpy.asarray(key), logits[sl])
                logp_a = jax.numpy.take_along_axis(
                    logp_all[sl], actions[..., None], -1)[..., 0]
                if not fut.done():
                    fut.set_result((np.asarray(actions),
                                    np.asarray(logp_a),
                                    np.asarray(values[sl]),
                                    self._version))

    # ------------------------------------------------------------- stats
    def get_stats(self) -> Dict[str, Any]:
        return {"iterations": self._iterations,
                "requests": self._requests,
                "max_batch_occupancy": self._max_occupancy,
                "weight_version": self._version}

    def ping(self) -> bool:
        return True


def create_inference_pool(module_spec: Dict, *, job: str = "",
                          batch_window_s: float = 0.002,
                          max_batch: int = 64, max_concurrency: int = 64,
                          num_cpus: float = 1):
    """Spawn an InferencePool actor with the async concurrency it needs."""
    import ray_tpu

    return ray_tpu.remote(InferencePool).options(
        max_concurrency=max_concurrency, num_cpus=num_cpus).remote(
            module_spec, job=job,
            batch_window_s=batch_window_s, max_batch=max_batch)


def llm_policy_pool(engine_kwargs: Optional[dict] = None, *,
                    name: str = "rl-llm", num_replicas: int = 1,
                    max_ongoing_requests: int = 64):
    """Batched-inference tier for LLM policies: a serve handle backed by
    ``llm_deployment()``.  Runners submit the episode-so-far prompt per
    step; the engine's iteration-level batching folds concurrent runners
    into shared decode steps and the radix prefix cache adopts the common
    trajectory prefix instead of re-prefilling it every step."""
    from ray_tpu import serve
    from ray_tpu.llm import llm_deployment

    app = llm_deployment(engine_kwargs, name=name,
                         num_replicas=num_replicas,
                         max_ongoing_requests=max_ongoing_requests,
                         stream_by_default=False)
    return serve.run(app, name=name, route_prefix=f"/{name}")
