"""RL training metrics (exported as ray_tpu_rllib_* on every node's
/metrics scrape; reference: rllib's env-steps/learner throughput stats —
folded through the same push->scrape->view pipeline the Serve/Data/Train/LLM
series ride).

One lazily-built singleton set per process; the ``job`` label keys every
series so several concurrently running algorithms (or a bench's A/B arms)
stay distinguishable, and the view layer sums/folds them per job.
"""

from __future__ import annotations

import threading
from typing import Dict

from ray_tpu._private import metrics as M

# Staleness is measured in POLICY VERSIONS (published weight generations
# between the fragment's behavior policy and the learner's current one) —
# small integers, so unit-width buckets at the bottom.
STALENESS_BOUNDARIES = (0, 1, 2, 3, 4, 6, 8, 12, 16, 32)
# One pooled forward serves this many concurrent act() requests.
INFER_BATCH_BOUNDARIES = (1, 2, 4, 8, 16, 32, 64, 128)

_lock = threading.Lock()
_metrics: Dict[str, M.Metric] = {}


def rllib_metrics() -> Dict[str, M.Metric]:
    """The process-local RL metric set (idempotent; re-instantiation by
    name adopts existing storage)."""
    global _metrics
    if not _metrics:
        with _lock:
            if not _metrics:
                _metrics = {
                    "env_steps": M.Counter(
                        "rllib_env_steps_total",
                        "environment steps sampled by env-runners, per job"),
                    "fragments": M.Counter(
                        "rllib_fragments_total",
                        "trajectory fragments consumed by the learner(s), "
                        "per job"),
                    "staleness": M.Histogram(
                        "rllib_fragment_staleness",
                        "policy-version lag of each consumed fragment "
                        "(published versions behind the learner), per job",
                        boundaries=STALENESS_BOUNDARIES),
                    "update_seconds": M.Histogram(
                        "rllib_learner_update_seconds",
                        "one learner update (grads + fold + apply), per job",
                        boundaries=M.PHASE_SECONDS_BOUNDARIES),
                    "allreduce_seconds": M.Histogram(
                        "rllib_learner_allreduce_seconds",
                        "gradient allreduce inside one learner update, "
                        "per job",
                        boundaries=M.PHASE_SECONDS_BOUNDARIES),
                    "infer_batch": M.Histogram(
                        "rllib_inference_batch_size",
                        "act() requests folded into one pooled forward "
                        "(Sebulba batched-inference occupancy), per job",
                        boundaries=INFER_BATCH_BOUNDARIES),
                    "infer_requests": M.Counter(
                        "rllib_inference_requests_total",
                        "act() requests served by InferencePool actors, "
                        "per job"),
                    "weight_version": M.Gauge(
                        "rllib_weight_version",
                        "latest policy version published to the weight "
                        "mailbox, per job"),
                    "runner_restarts": M.Counter(
                        "rllib_runner_restarts_total",
                        "env-runner actors respawned after death, per job"),
                }
    return _metrics
