"""SAC: soft actor-critic for continuous control.

Counterpart of the reference's SAC (reference: rllib/algorithms/sac/sac.py —
twin Q, tanh-squashed Gaussian actor, automatic entropy temperature;
torch loss in sac/torch/sac_torch_learner.py).  TPU-first shape: the whole
update — critic TD against the entropy-regularized clipped double-Q target,
actor reparameterized gradient, temperature loss, polyak target sync — is
ONE jitted ``lax.scan`` over minibatches; a single optimizer steps the
combined {actor, critic, log_alpha} pytree with stop-gradients partitioning
the three losses (no per-network Python optimizer loop).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.rl_module import SquashedGaussianModule, TwinQModule


# the transition store is DQN's ReplayBuffer with a float action spec
# (one ring implementation to maintain, not two)
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer


class SACEnvRunner:
    """Stochastic-policy transition sampler over K vectorized envs (1-step;
    time-limit truncations bootstrap through ``final_obs``)."""

    def __init__(self, env_name: str, num_envs: int, rollout_length: int,
                 module_spec: Dict, seed: int = 0):
        import sys

        if "jax" in sys.modules:
            import jax._src.xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        else:
            initialized = False
        if not initialized:
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax

        from ray_tpu.rllib.env import make_vector_env

        self.env = make_vector_env(env_name, num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.actor = SquashedGaussianModule(
            observation_size=module_spec["observation_size"],
            action_size=module_spec["action_size"],
            max_action=module_spec["max_action"],
            hidden=module_spec["hidden"])
        self._key = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed + 13)
        self._sample = jax.jit(self.actor.sample)
        self.obs = self.env.reset()
        self._ep_return = np.zeros(num_envs, np.float32)
        self._recent_returns: list = []

    def sample(self, params, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        out = {k: [] for k in ("obs", "actions", "rewards", "next_obs",
                               "dones")}
        for _ in range(self.rollout_length):
            if random_actions:
                a = self._np_rng.uniform(
                    -self.actor.max_action, self.actor.max_action,
                    (self.num_envs, self.actor.action_size)).astype(np.float32)
            else:
                self._key, sub = jax.random.split(self._key)
                a, _ = self._sample(params, jnp.asarray(self.obs), sub)
                a = np.asarray(a)
            nxt, r, term, trunc, info = self.env.step(a[:, 0]
                                                      if a.shape[1] == 1
                                                      else a)
            done = term | trunc
            # bootstrap target uses the PRE-reset obs at done slots
            succ = np.where(done[:, None], info["final_obs"], nxt)
            out["obs"].append(self.obs.copy())
            out["actions"].append(a)
            out["rewards"].append(r)
            out["next_obs"].append(succ)
            out["dones"].append(term.astype(np.float32))  # not truncations
            self._ep_return += r
            for i in np.nonzero(done)[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self.obs = nxt
        self._recent_returns = self._recent_returns[-100:]
        return {k: np.concatenate(v) for k, v in out.items()}

    def get_metrics(self) -> Dict[str, Any]:
        r = self._recent_returns
        return {"episode_return_mean": float(np.mean(r)) if r else None,
                "episodes": len(r)}

    def ping(self) -> bool:
        return True


def _sac_update(actor_mod, critic_mod, tx, params, target_q, opt_state,
                key, batches, *, tau: float, target_entropy: float):
    import jax
    import jax.numpy as jnp

    def loss_fn(p, target_q, mb, key):
        alpha = jnp.exp(p["log_alpha"])
        k1, k2 = jax.random.split(key)

        # ------- critic: TD against entropy-regularized double-Q target
        a_next, logp_next = actor_mod.sample(
            jax.lax.stop_gradient(p["actor"]), mb["next_obs"], k1)
        q1_t, q2_t = critic_mod.q_values(target_q, mb["next_obs"], a_next)
        y = mb["rewards"] + mb["discounts"] * (1.0 - mb["dones"]) * (
            jnp.minimum(q1_t, q2_t)
            - jax.lax.stop_gradient(alpha) * logp_next)
        y = jax.lax.stop_gradient(y)
        q1, q2 = critic_mod.q_values(p["critic"], mb["obs"], mb["actions"])
        critic_loss = ((q1 - y) ** 2 + (q2 - y) ** 2).mean()

        # ------- actor: reparameterized, against frozen critics
        a_pi, logp_pi = actor_mod.sample(p["actor"], mb["obs"], k2)
        q1_pi, q2_pi = critic_mod.q_values(
            jax.lax.stop_gradient(p["critic"]), mb["obs"], a_pi)
        actor_loss = (jax.lax.stop_gradient(alpha) * logp_pi
                      - jnp.minimum(q1_pi, q2_pi)).mean()

        # ------- temperature (automatic entropy tuning)
        alpha_loss = (-jnp.exp(p["log_alpha"])
                      * jax.lax.stop_gradient(logp_pi + target_entropy)
                      ).mean()
        total = critic_loss + actor_loss + alpha_loss
        return total, (critic_loss, actor_loss, alpha)

    def body(carry, inp):
        params, target_q, opt_state = carry
        mb, k = inp
        (_, (c_l, a_l, alpha)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, target_q, mb, k)
        import optax

        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_q = jax.tree_util.tree_map(
            lambda t, s: (1.0 - tau) * t + tau * s, target_q,
            params["critic"])
        return (params, target_q, opt_state), (c_l, a_l, alpha)

    n_mb = batches["obs"].shape[0]
    keys = jax.random.split(key, n_mb)
    (params, target_q, opt_state), (c_ls, a_ls, alphas) = jax.lax.scan(
        body, (params, target_q, opt_state), (batches, keys))
    return params, target_q, opt_state, jnp.mean(c_ls), jnp.mean(a_ls), \
        alphas[-1]


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 64
        self.training_params = {
            "lr": 3e-4,
            "gamma": 0.99,
            "tau": 0.005,
            "buffer_size": 200_000,
            "batch_size": 256,
            "num_updates_per_iter": 512,  # 1 grad step per env step (SAC standard)
            "learning_starts": 1_500,
            "random_warmup": True,
        }

    @property
    def algo_class(self):
        return SAC


class SAC(Algorithm):
    def setup(self, config: SACConfig) -> None:
        import jax
        import optax

        from ray_tpu.rllib.env import make_vector_env

        if config.learner_platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        probe = make_vector_env(config.env, 1, seed=0)
        if not getattr(probe, "continuous", False):
            raise ValueError(f"SAC needs a continuous-action env; "
                             f"{config.env} is discrete")
        p = config.training_params
        spec = {"observation_size": probe.observation_size,
                "action_size": probe.action_size,
                "max_action": probe.max_action,
                "hidden": tuple(config.model.get("hidden", (64, 64)))}
        self.actor_mod = SquashedGaussianModule(
            observation_size=spec["observation_size"],
            action_size=spec["action_size"],
            max_action=spec["max_action"], hidden=spec["hidden"])
        self.critic_mod = TwinQModule(
            observation_size=spec["observation_size"],
            action_size=spec["action_size"], hidden=spec["hidden"])
        ka, kc = jax.random.split(jax.random.PRNGKey(config.seed))
        self.params = {
            "actor": self.actor_mod.init(ka),
            "critic": self.critic_mod.init(kc),
            "log_alpha": jax.numpy.asarray(0.0),
        }
        self.target_q = self.params["critic"]
        self.tx = optax.adam(p["lr"])
        self.opt_state = self.tx.init(self.params)
        self._key = jax.random.PRNGKey(config.seed + 1)
        self._update = jax.jit(functools.partial(
            _sac_update, self.actor_mod, self.critic_mod, self.tx,
            tau=p["tau"], target_entropy=-float(spec["action_size"])))

        self.buffer = ReplayBuffer(
            p["buffer_size"], spec["observation_size"], seed=config.seed,
            action_shape=(spec["action_size"],), action_dtype=np.float32)
        self._steps_sampled = 0

        runner_kwargs = dict(
            env_name=config.env, num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec=spec, seed=config.seed)
        self._runner_actors = []
        self._local_runner = None
        if config.num_env_runners <= 0:
            self._local_runner = SACEnvRunner(**runner_kwargs)
        else:
            from ray_tpu.rllib.algorithms.algorithm import build_runner_actors

            self._runner_actors = build_runner_actors(
                config, SACEnvRunner, runner_kwargs)

    def training_step(self) -> Dict[str, Any]:
        import jax

        import ray_tpu

        p = self.config.training_params
        warmup = p["random_warmup"] and \
            self._steps_sampled < p["learning_starts"]
        if self._local_runner is not None:
            batches = [self._local_runner.sample(self.params["actor"],
                                                 random_actions=warmup)]
            metrics = [self._local_runner.get_metrics()]
        else:
            wref = ray_tpu.put(self.params["actor"])
            batches = ray_tpu.get([r.sample.remote(wref, warmup)
                                   for r in self._runner_actors])
            metrics = ray_tpu.get([r.get_metrics.remote()
                                   for r in self._runner_actors])
        for b in batches:
            # 1-step transitions: constant per-step discount
            disc = np.full(len(b["rewards"]), p["gamma"], np.float32)
            self.buffer.add_batch(b["obs"], b["actions"], b["rewards"],
                                  b["next_obs"], disc, b["dones"])
            self._steps_sampled += len(b["rewards"])

        stats: Dict[str, Any] = {}
        if self._steps_sampled >= p["learning_starts"]:
            idx = self.buffer.sample_indices(p["num_updates_per_iter"],
                                             p["batch_size"])
            mbs = self.buffer.gather(idx)
            self._key, sub = jax.random.split(self._key)
            (self.params, self.target_q, self.opt_state, c_l, a_l,
             alpha) = self._update(self.params, self.target_q,
                                   self.opt_state, sub, mbs)
            stats = {"critic_loss": float(c_l), "actor_loss": float(a_l),
                     "alpha": float(alpha)}
        rets = [m["episode_return_mean"] for m in metrics
                if m["episode_return_mean"] is not None]
        return {"episode_return_mean":
                float(np.mean(rets)) if rets else None,
                "steps_sampled": self._steps_sampled, **stats}

    def evaluate(self, n_episodes: int = 5) -> Dict[str, float]:
        import jax.numpy as jnp

        from ray_tpu.rllib.env import make_vector_env

        env = make_vector_env(self.config.env, 1,
                              seed=self.config.seed + 99)
        returns = []
        for _ in range(n_episodes):
            obs = env.reset()
            total = 0.0
            while True:
                a = np.asarray(self.actor_mod.forward_inference(
                    self.params["actor"], jnp.asarray(obs)))
                obs, r, term, trunc, _ = env.step(
                    a[:, 0] if a.shape[1] == 1 else a)
                total += float(r[0])
                if bool(term[0] or trunc[0]):
                    break
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}
