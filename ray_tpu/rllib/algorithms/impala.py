"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Counterpart of the reference's IMPALA (reference:
rllib/algorithms/impala/impala.py:132-133 — actors sample continuously into
queues, the learner consumes without a synchronization barrier;
vtrace_torch.py for the correction math), rebuilt on the Podracer
subsystem (rllib/podracer/):

- **streaming (default, ``async_stream=True``)**: every runner executes a
  continuous ``run_stream`` loop; fragments arrive via per-item streaming
  refs the moment each is sealed, weights travel through the versioned
  mailbox (one put per version, N runner gets), and a SIGKILLed runner is
  respawned mid-stream without stalling the survivors;
- **relaunch (``async_stream=False``, kept for bench A/B)**: the PR-8-era
  loop — one in-flight ``sample()`` per runner, relaunched per fragment —
  except weights now also come from the mailbox instead of riding every
  sample call as an argument;
- **Sebulba (``inference_mode="pool"``)**: runners stop doing local
  inference entirely; an async InferencePool actor serves batched
  forwards for the whole gang;
- ``num_learners=K`` replaces the driver-local learner with a gang of K
  learner actors folding gradients through a persistent collective group
  (optionally ``learner_quorum=K-1`` so a straggler never stalls a round).

Sampled fragments are 1+ policy versions stale either way: the jitted
learner recomputes target logp/values and corrects with clipped importance
ratios (ops/vtrace.py) in a single pass (no PPO-style epochs).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2  # async needs actor runners
        self.training_params = {
            "lr": 5e-4,
            "gamma": 0.99,
            "rho_clip": 1.0,
            "c_clip": 1.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "grad_clip": 40.0,
        }
        # podracer knobs (see module docstring / .podracer())
        self.async_stream = True
        self.fragments_per_call = 8
        self.inference_mode = "local"  # "local" (Anakin) | "pool" (Sebulba)
        self.learner_quorum: Optional[int] = None
        self.publish_every = 1
        self.batch_window_s = 0.002

    def podracer(self, *, async_stream: Optional[bool] = None,
                 fragments_per_call: Optional[int] = None,
                 inference_mode: Optional[str] = None,
                 learner_quorum: Optional[int] = None,
                 publish_every: Optional[int] = None,
                 batch_window_s: Optional[float] = None) -> "IMPALAConfig":
        if async_stream is not None:
            self.async_stream = async_stream
        if fragments_per_call is not None:
            self.fragments_per_call = fragments_per_call
        if inference_mode is not None:
            if inference_mode not in ("local", "pool"):
                raise ValueError("inference_mode is 'local' or 'pool'")
            self.inference_mode = inference_mode
        if learner_quorum is not None:
            self.learner_quorum = learner_quorum
        if publish_every is not None:
            self.publish_every = publish_every
        if batch_window_s is not None:
            self.batch_window_s = batch_window_s
        return self

    @property
    def algo_class(self):
        return IMPALA


class IMPALA(Algorithm):
    def setup(self, config: IMPALAConfig) -> None:
        from ray_tpu._private.ids import _fast_unique
        from ray_tpu.rllib.algorithms.algorithm import (build_module_spec,
                                                        build_runner_actors)
        from ray_tpu.rllib.env.env_runner import EnvRunner
        from ray_tpu.rllib.podracer import (FragmentStream, LearnerGang,
                                            PodracerLearner,
                                            create_inference_pool)

        self._module_spec = build_module_spec(config)
        if config.num_env_runners <= 0:
            raise ValueError("IMPALA needs actor env-runners "
                             "(num_env_runners >= 1): the sampling is async")
        self._job = f"impala-{_fast_unique(4).hex()}"

        if config.num_learners >= 1:
            self.learner: Any = LearnerGang(
                self._module_spec, config.training_params,
                num_learners=config.num_learners, job=self._job,
                seed=config.seed, quorum=config.learner_quorum,
                platform=config.learner_platform,
                publish_every=config.publish_every)
        else:
            self.learner = PodracerLearner(
                self._module_spec, config.training_params, seed=config.seed,
                job=self._job, platform=config.learner_platform,
                publish_every=config.publish_every)
        # v0 weights: ONE versioned put; runners/pool poll the mailbox
        self._pub_version = self.learner.publish()

        self._pool = None
        self._runner_kwargs = dict(
            env_name=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec=self._module_spec,
            seed=config.seed,
            job=self._job)
        if config.inference_mode == "pool":
            self._pool = create_inference_pool(
                self._module_spec, job=self._job,
                batch_window_s=config.batch_window_s, num_cpus=0)
            self._runner_kwargs["inference"] = self._pool
        self._runners = build_runner_actors(
            config, EnvRunner, self._runner_kwargs, index_key="runner_idx")
        self._steps_sampled = 0
        self._sample_t0 = time.monotonic()
        self._last_returns: Dict[Any, float] = {}

        if config.async_stream:
            self._stream: Optional[FragmentStream] = FragmentStream(
                self._runners, fragments_per_call=config.fragments_per_call,
                respawn=self._respawn_runner, job=self._job)
            self._inflight: Dict[Any, Any] = {}
        else:
            self._stream = None
            # one in-flight sample per runner; no weights argument — the
            # runner polls the mailbox at the top of every sample()
            self._inflight = {r.sample.remote(): r for r in self._runners}

    def _respawn_runner(self, idx: int):
        import ray_tpu

        from ray_tpu.rllib.env.env_runner import EnvRunner

        kw = {**self._runner_kwargs,
              "seed": self.config.seed + 1000 * (idx + 1),
              "runner_idx": idx}
        handle = ray_tpu.remote(EnvRunner).options(num_cpus=1).remote(**kw)
        self._runners[idx] = handle
        return handle

    # ------------------------------------------------------------ one iter
    def _consume(self, fragment_ref, fragment) -> list:
        """One fragment into the learner (driver-local call or gang round
        dispatch by ref); returns any completed stats dicts."""
        from ray_tpu.rllib.podracer import LearnerGang

        if isinstance(self.learner, LearnerGang):
            return self.learner.submit(fragment_ref)
        return [self.learner.update(fragment)]

    def _result(self, n_fragments: int, stats_list: list) -> Dict[str, Any]:
        if stats_list:
            v = int(max(s.get("weight_version", 0) for s in stats_list))
            if v:
                self._pub_version = v
        returns = [r for r in self._last_returns.values() if np.isfinite(r)]
        dt = time.monotonic() - self._sample_t0
        last = stats_list[-1] if stats_list else {}
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._steps_sampled,
            "env_steps_per_s": self._steps_sampled / max(dt, 1e-9),
            "num_fragments_consumed": n_fragments,
            "policy_version": self._pub_version,
            **{f"learner/{k}": v for k, v in last.items()},
        }

    def training_step(self) -> Dict[str, Any]:
        if self._stream is None:
            return self._relaunch_step()
        from ray_tpu.rllib._metrics import rllib_metrics

        staleness = rllib_metrics()["staleness"]
        frags = self._stream.next_fragments(timeout_s=300)
        stats_list: list = []
        for idx, ref, frag in frags:
            staleness.observe(
                max(self._pub_version - frag["policy_version"], 0),
                {"job": self._job})
            self._steps_sampled += int(frag["batch"]["rewards"].size)
            self._last_returns[idx] = frag["episode_return_mean"]
            stats_list += self._consume(ref, frag)
        return self._result(len(frags), stats_list)

    def _relaunch_step(self) -> Dict[str, Any]:
        """PR-8-era control flow, kept as the bench A/B baseline: consume
        whatever finished (no barrier), update, relaunch the drained
        runners — one actor round trip per fragment."""
        import ray_tpu

        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=300)
        if not ready:
            raise TimeoutError("no env-runner produced a fragment in 300s")
        # opportunistically grab anything else already done
        more, _ = ray_tpu.wait(
            [r for r in self._inflight if r not in ready],
            num_returns=len(self._inflight) - len(ready), timeout=0)
        ready += more
        batches = ray_tpu.get(ready)
        done_runners = [self._inflight.pop(ref) for ref in ready]
        # metrics BEFORE relaunching: the runner actor is serial, so a
        # get_metrics queued behind a fresh sample() would block this step
        # on a whole new fragment — exactly the barrier IMPALA removes
        metric_refs = [r.get_metrics.remote() for r in done_runners]

        # one update per fragment: every fragment has the same (T, K) shape,
        # so the jitted update compiles ONCE (a variable-width concat would
        # recompile per distinct ready-count)
        stats_list: list = []
        for ref, b in zip(ready, batches):
            stats_list += self._consume(ref, b)
            self._steps_sampled += int(b["rewards"].size)

        # relaunch the drained runners; they pick the learner's freshly
        # published version out of the mailbox themselves (the old path
        # re-put the full weight pytree here and shipped it per call)
        for r in done_runners:
            self._inflight[r.sample.remote()] = r

        for r, m in zip(done_runners, ray_tpu.get(metric_refs)):
            self._last_returns[r._actor_id_hex()] = m["episode_return_mean"]
        return self._result(len(batches), stats_list)

    def stop(self) -> None:
        import ray_tpu

        from ray_tpu.rllib.podracer import LearnerGang

        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        if self._pool is not None:
            try:
                ray_tpu.kill(self._pool)
            except Exception:
                pass
        if isinstance(self.learner, LearnerGang):
            self.learner.stop()
        self._runners = []
        self._inflight = {}
        self._stream = None
        self._pool = None
