"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Counterpart of the reference's IMPALA (reference:
rllib/algorithms/impala/impala.py:132-133 — actors sample continuously into
queues, the learner consumes without a synchronization barrier;
vtrace_torch.py for the correction math).  Control flow here:

- every runner actor always has ONE sample() in flight; training_step waits
  for whichever fragments are ready (``ray_tpu.wait``), updates with those,
  and immediately relaunches the runners with the new weights — runners
  never wait for the learner, the learner never waits for stragglers;
- sampled fragments are therefore 1+ policy versions stale: the jitted
  learner recomputes target logp/values and corrects with clipped
  importance ratios (ops/vtrace.py) in a single pass (no PPO-style epochs).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2  # async needs actor runners
        self.training_params = {
            "lr": 5e-4,
            "gamma": 0.99,
            "rho_clip": 1.0,
            "c_clip": 1.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "grad_clip": 40.0,
        }

    @property
    def algo_class(self):
        return IMPALA


class IMPALA(Algorithm):
    def setup(self, config: IMPALAConfig) -> None:
        import ray_tpu

        from ray_tpu.rllib.algorithms.algorithm import (build_module_spec,
                                                        build_runner_actors)

        self._module_spec = build_module_spec(config)
        self.learner = _ImpalaLearner(
            self._module_spec, config.training_params, seed=config.seed,
            platform=config.learner_platform)

        if config.num_env_runners <= 0:
            raise ValueError("IMPALA needs actor env-runners "
                             "(num_env_runners >= 1): the sampling is async")
        from ray_tpu.rllib.env.env_runner import EnvRunner

        self._runners = build_runner_actors(config, EnvRunner, dict(
            env_name=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec=self._module_spec,
            seed=config.seed))
        # one in-flight sample per runner, launched with the initial weights
        wref = ray_tpu.put(self.learner.get_weights())
        self._inflight: Dict[Any, Any] = {
            r.sample.remote(wref): r for r in self._runners}
        self._steps_sampled = 0
        self._sample_t0 = time.monotonic()

    # ------------------------------------------------------------ one iter
    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        # consume whatever is ready — NO barrier across runners
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=300)
        if not ready:
            raise TimeoutError("no env-runner produced a fragment in 300s")
        # opportunistically grab anything else already done
        more, _ = ray_tpu.wait(
            [r for r in self._inflight if r not in ready],
            num_returns=len(self._inflight) - len(ready), timeout=0)
        ready += more
        batches = ray_tpu.get(ready)
        done_runners = [self._inflight.pop(ref) for ref in ready]
        # metrics BEFORE relaunching: the runner actor is serial, so a
        # get_metrics queued behind a fresh sample() would block this step
        # on a whole new fragment — exactly the barrier IMPALA removes
        metric_refs = [r.get_metrics.remote() for r in done_runners]

        # one update per fragment: every fragment has the same (T, K) shape,
        # so the jitted update compiles ONCE (a variable-width concat would
        # recompile per distinct ready-count)
        for b in batches:
            stats = self.learner.update(b)
            self._steps_sampled += int(b["rewards"].size)

        # relaunch the drained runners with the new weights; the others keep
        # sampling their (now stale) policies — that staleness is exactly
        # what V-trace corrects
        wref = ray_tpu.put(self.learner.get_weights())
        for r in done_runners:
            self._inflight[r.sample.remote(wref)] = r

        metrics = ray_tpu.get(metric_refs)
        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        dt = time.monotonic() - self._sample_t0
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._steps_sampled,
            "env_steps_per_s": self._steps_sampled / max(dt, 1e-9),
            "num_fragments_consumed": len(batches),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runners = []
        self._inflight = {}


class _ImpalaLearner:
    """Single-pass V-trace learner; whole update under one jit (the IMPALA
    counterpart of the PPO JaxLearner in core/learner.py)."""

    def __init__(self, module_spec: Dict, config: Dict, seed: int = 0,
                 platform=None):
        if platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax
        import optax

        from ray_tpu.rllib.core.rl_module import DiscretePolicyModule

        self.module = DiscretePolicyModule(**module_spec)
        self.config = dict(config)
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 40.0)),
            optax.adam(self.config.get("lr", 5e-4)),
        )
        self.opt_state = self.tx.init(self.params)
        self._update = jax.jit(functools.partial(
            _impala_update, self.module, self.tx,
            gamma=self.config.get("gamma", 0.99),
            rho_clip=self.config.get("rho_clip", 1.0),
            c_clip=self.config.get("c_clip", 1.0),
            vf_loss_coeff=self.config.get("vf_loss_coeff", 0.5),
            entropy_coeff=self.config.get("entropy_coeff", 0.01),
        ))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> None:
        self.params = params


def _impala_update(module, tx, params, opt_state, batch, *, gamma, rho_clip,
                   c_clip, vf_loss_coeff, entropy_coeff):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.ops.vtrace import vtrace_from_fragments

    T, K = batch["rewards"].shape
    obs = batch["obs"].reshape(T * K, -1)
    actions = batch["actions"].reshape(T * K)
    dones = batch["terminated"] | batch["truncated"]

    def loss_fn(p):
        # target policy/value under CURRENT params; behavior logp/values in
        # the batch came from the stale runner weights
        logp, entropy = module.logp_entropy(p, obs, actions)
        v = module.value(p, obs)
        logp_t = logp.reshape(T, K)
        v_t = v.reshape(T, K)
        # successor values under the current value net: v[t+1] inside the
        # fragment, runner-provided bootstrap at the tail, 0/bootstrap at
        # episode boundaries (next_values bakes those in; scale by the
        # ratio of current to behavior tail values is not needed — vtrace
        # uses the current estimates everywhere except boundaries where the
        # runner's bootstrap stands in)
        nv = jnp.concatenate([v_t[1:], batch["next_values"][-1:]], axis=0)
        nv = jnp.where(dones, batch["next_values"], nv)
        vs, pg_adv = vtrace_from_fragments(
            batch["logp"], jax.lax.stop_gradient(logp_t),
            batch["rewards"], jax.lax.stop_gradient(v_t),
            jax.lax.stop_gradient(nv), dones, gamma, rho_clip, c_clip)
        pg_loss = -(jax.lax.stop_gradient(pg_adv) * logp_t).mean()
        vf_loss = 0.5 * ((v_t - jax.lax.stop_gradient(vs)) ** 2).mean()
        loss = (pg_loss + vf_loss_coeff * vf_loss
                - entropy_coeff * entropy.mean())
        return loss, {
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy.mean(),
            "mean_vtrace_target": vs.mean(),
            "mean_is_ratio": jnp.exp(
                jax.lax.stop_gradient(logp_t) - batch["logp"]).mean(),
        }

    (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    stats["total_loss"] = loss
    return params, opt_state, stats
