"""PPO on the new-stack shapes: EnvRunner actors → JaxLearner → weight sync.

Counterpart of the reference's PPO (reference: rllib/algorithms/ppo/ppo.py:67
PPOConfig, :427 training_step: synchronous_parallel_sample →
learner_group.update → env_runner_group.sync_weights :525).  The loss/GAE
math lives in the jitted learner (core/learner.py); this module is the
orchestration: parallel sampling on actor env-runners, one device update,
broadcast weights through the object store.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.env.env_runner import EnvRunner


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.training_params = {
            "lr": 3e-4,
            "gamma": 0.99,
            "gae_lambda": 0.95,
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "vf_clip_param": 10.0,
            "entropy_coeff": 0.0,
            "num_epochs": 6,
            "minibatch_size": 256,
            "grad_clip": 0.5,
        }

    @property
    def algo_class(self):
        return PPO


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        from ray_tpu.rllib.algorithms.algorithm import (build_module_spec,
                                                        build_runner_actors)

        self._module_spec = build_module_spec(config)
        self.learner_group = LearnerGroup(
            self._module_spec, config.training_params,
            num_learners=config.num_learners, seed=config.seed,
            platform=config.learner_platform)

        self._local_runner = None
        self._runner_actors = []
        if config.num_env_runners <= 0:
            self._local_runner = EnvRunner(
                env_name=config.env,
                num_envs=config.num_envs_per_env_runner,
                rollout_length=config.rollout_fragment_length,
                module_spec=self._module_spec,
                seed=config.seed)
        else:
            self._runner_actors = build_runner_actors(
                config, self._module_spec)

    # ------------------------------------------------------------ one iter
    def training_step(self) -> Dict[str, Any]:
        weights = self.learner_group.get_weights()

        if self._local_runner is not None:
            batches = [self._local_runner.sample(weights)]
            metrics = [self._local_runner.get_metrics()]
        else:
            import ray_tpu

            # ship weights once via the object store; every runner borrows
            # the same copy (reference: sync_weights broadcast, ppo.py:525)
            wref = ray_tpu.put(weights)
            batches = ray_tpu.get(
                [r.sample.remote(wref) for r in self._runner_actors])
            metrics = ray_tpu.get(
                [r.get_metrics.remote() for r in self._runner_actors])

        batch = {k: np.concatenate([b[k] for b in batches], axis=1)
                 for k in batches[0]}
        stats = self.learner_group.update(batch)

        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": int(
                sum(m["num_env_steps_sampled_lifetime"] for m in metrics)),
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        self.learner_group.shutdown()
        for r in self._runner_actors:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runner_actors = []
