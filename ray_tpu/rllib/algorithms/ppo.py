"""PPO on the new-stack shapes: EnvRunner actors → JaxLearner → weight sync.

Counterpart of the reference's PPO (reference: rllib/algorithms/ppo/ppo.py:67
PPOConfig, :427 training_step: synchronous_parallel_sample →
learner_group.update → env_runner_group.sync_weights :525).  The loss/GAE
math lives in the jitted learner (core/learner.py); this module is the
orchestration: parallel sampling on actor env-runners, one device update,
broadcast weights through the object store.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.env.env_runner import EnvRunner


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.training_params = {
            "lr": 3e-4,
            "gamma": 0.99,
            "gae_lambda": 0.95,
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "vf_clip_param": 10.0,
            "entropy_coeff": 0.0,
            "num_epochs": 6,
            "minibatch_size": 256,
            "grad_clip": 0.5,
        }

    @property
    def algo_class(self):
        return PPO


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        probe = make_vector_env(config.env, 1, seed=0)
        self._module_spec = {
            "observation_size": probe.observation_size,
            "num_actions": probe.num_actions,
            "hidden": tuple(config.model.get("hidden", (64, 64))),
        }
        self.learner_group = LearnerGroup(
            self._module_spec, config.training_params,
            num_learners=config.num_learners, seed=config.seed,
            platform=config.learner_platform)

        runner_args = dict(
            env_name=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec=self._module_spec,
        )
        self._local_runner = None
        self._runner_actors = []
        if config.num_env_runners <= 0:
            self._local_runner = EnvRunner(**runner_args, seed=config.seed)
        else:
            import ray_tpu

            runner_cls = ray_tpu.remote(EnvRunner)
            self._runner_actors = [
                runner_cls.options(num_cpus=1).remote(
                    **runner_args, seed=config.seed + 1000 * (i + 1))
                for i in range(config.num_env_runners)
            ]

    # ------------------------------------------------------------ one iter
    def training_step(self) -> Dict[str, Any]:
        weights = self.learner_group.get_weights()

        if self._local_runner is not None:
            batches = [self._local_runner.sample(weights)]
            metrics = [self._local_runner.get_metrics()]
        else:
            import ray_tpu

            # ship weights once via the object store; every runner borrows
            # the same copy (reference: sync_weights broadcast, ppo.py:525)
            wref = ray_tpu.put(weights)
            batches = ray_tpu.get(
                [r.sample.remote(wref) for r in self._runner_actors])
            metrics = ray_tpu.get(
                [r.get_metrics.remote() for r in self._runner_actors])

        batch = {k: np.concatenate([b[k] for b in batches], axis=1)
                 for k in batches[0]}
        stats = self.learner_group.update(batch)

        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": int(
                sum(m["num_env_steps_sampled_lifetime"] for m in metrics)),
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        self.learner_group.shutdown()
        for r in self._runner_actors:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runner_actors = []
