"""PPO on the new-stack shapes: EnvRunner actors → JaxLearner → weight sync.

Counterpart of the reference's PPO (reference: rllib/algorithms/ppo/ppo.py:67
PPOConfig, :427 training_step: synchronous_parallel_sample →
learner_group.update → env_runner_group.sync_weights :525).  The loss/GAE
math lives in the jitted learner (core/learner.py); this module is the
orchestration: parallel sampling on actor env-runners, one device update,
broadcast weights through the object store.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.env.env_runner import EnvRunner


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.training_params = {
            "lr": 3e-4,
            "gamma": 0.99,
            "gae_lambda": 0.95,
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "vf_clip_param": 10.0,
            "entropy_coeff": 0.0,
            "num_epochs": 6,
            "minibatch_size": 256,
            "grad_clip": 0.5,
        }

    @property
    def algo_class(self):
        return PPO


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        from ray_tpu.rllib.algorithms.algorithm import (build_module_spec,
                                                        build_runner_actors)

        if config.policies:
            self._setup_multi_agent(config)
            return
        self._multi = False
        self._module_spec = build_module_spec(config)
        self.learner_group = LearnerGroup(
            self._module_spec, config.training_params,
            num_learners=config.num_learners, seed=config.seed,
            platform=config.learner_platform)

        self._local_runner = None
        self._runner_actors = []
        runner_kwargs = dict(
            env_name=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec=self._module_spec,
            seed=config.seed)
        if config.num_env_runners <= 0:
            self._local_runner = EnvRunner(**runner_kwargs)
        else:
            self._runner_actors = build_runner_actors(
                config, EnvRunner, runner_kwargs)

    # ------------------------------------------------- multi-agent setup
    def _setup_multi_agent(self, config: PPOConfig) -> None:
        """Per-policy learners over a multi-agent runner (reference: PPO's
        multi-agent training_step updating each module id's learner;
        rllib/env/multi_agent_env_runner.py).  Agents sharing a policy are
        extra env columns, so each policy reuses the single-agent learner."""
        from ray_tpu.rllib.env.multi_agent import (MultiAgentEnvRunner,
                                                   make_multi_agent_env)

        self._multi = True
        probe = make_multi_agent_env(config.env, 1, seed=0)
        specs = {}
        for a in probe.agents:
            pid = config.policy_mapping_fn(a)
            spec = {"observation_size": probe.observation_sizes[a],
                    "num_actions": probe.num_actions[a],
                    "hidden": tuple(config.model.get("hidden", (64, 64)))}
            if pid in specs and specs[pid] != spec:
                raise ValueError(
                    f"agents sharing policy {pid!r} have different spaces")
            specs[pid] = spec
        unknown = set(specs) - set(config.policies)
        if unknown:
            raise ValueError(
                f"policy_mapping_fn produced unknown policies {unknown}; "
                f"declared: {config.policies}")
        unmapped = set(config.policies) - set(specs)
        if unmapped:
            raise ValueError(
                f"declared policies {sorted(unmapped)} are mapped to no "
                f"agent (typo in policy_mapping_fn?)")
        self._policy_specs = specs
        self.learner_groups = {
            pid: LearnerGroup(spec, config.training_params,
                              num_learners=config.num_learners,
                              seed=config.seed + i,
                              platform=config.learner_platform)
            for i, (pid, spec) in enumerate(sorted(specs.items()))}
        self._runner_actors = []
        runner_kwargs = dict(
            env_name=config.env, num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            policy_specs=specs,
            policy_mapping_fn=config.policy_mapping_fn, seed=config.seed)
        if config.num_env_runners <= 0:
            self._local_runner = MultiAgentEnvRunner(**runner_kwargs)
        else:
            from ray_tpu.rllib.algorithms.algorithm import build_runner_actors

            self._local_runner = None
            self._runner_actors = build_runner_actors(
                config, MultiAgentEnvRunner, runner_kwargs)

    def _training_step_multi(self) -> Dict[str, Any]:
        import ray_tpu

        weights = {pid: g.get_weights()
                   for pid, g in self.learner_groups.items()}
        if self._local_runner is not None:
            by_policy = [self._local_runner.sample(weights)]
            metrics = [self._local_runner.get_metrics()]
        else:
            wref = ray_tpu.put(weights)
            by_policy = ray_tpu.get(
                [r.sample.remote(wref) for r in self._runner_actors])
            metrics = ray_tpu.get(
                [r.get_metrics.remote() for r in self._runner_actors])
        stats = {}
        for pid, group in self.learner_groups.items():
            batch = {k: np.concatenate([b[pid][k] for b in by_policy], axis=1)
                     for k in by_policy[0][pid]}
            for k, v in group.update(batch).items():
                stats[f"learner/{pid}/{k}"] = v
        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": int(
                sum(m["num_env_steps_sampled_lifetime"] for m in metrics)),
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
            **stats,
        }

    # ------------------------------------------------------------ one iter
    def training_step(self) -> Dict[str, Any]:
        if self._multi:
            return self._training_step_multi()
        weights = self.learner_group.get_weights()

        if self._local_runner is not None:
            batches = [self._local_runner.sample(weights)]
            metrics = [self._local_runner.get_metrics()]
        else:
            import ray_tpu

            # ship weights once via the object store; every runner borrows
            # the same copy (reference: sync_weights broadcast, ppo.py:525)
            wref = ray_tpu.put(weights)
            batches = ray_tpu.get(
                [r.sample.remote(wref) for r in self._runner_actors])
            metrics = ray_tpu.get(
                [r.get_metrics.remote() for r in self._runner_actors])

        batch = {k: np.concatenate([b[k] for b in batches], axis=1)
                 for k in batches[0]}
        stats = self.learner_group.update(batch)

        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": int(
                sum(m["num_env_steps_sampled_lifetime"] for m in metrics)),
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        if getattr(self, "_multi", False):
            for g in self.learner_groups.values():
                g.shutdown()
        else:
            self.learner_group.shutdown()
        for r in self._runner_actors:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runner_actors = []
