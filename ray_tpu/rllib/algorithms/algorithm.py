"""Algorithm + AlgorithmConfig: the RL driver loop.

Counterpart of the reference's Algorithm (reference:
rllib/algorithms/algorithm.py:227 — a Tune Trainable whose ``train()`` runs
one ``training_step`` and aggregates metrics; fluent AlgorithmConfig
rllib/algorithms/algorithm_config.py).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, Optional


class AlgorithmConfig:
    """Fluent config (reference: rllib/algorithms/algorithm_config.py).

    cfg = (PPOConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=2).training(lr=3e-4))
    """

    def __init__(self):
        self.env: Optional[str] = None
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 8
        self.rollout_fragment_length: int = 64
        self.num_learners: int = 0
        self.learner_platform: Optional[str] = None
        self.seed: int = 0
        self.model: Dict[str, Any] = {"hidden": (64, 64)}
        self.training_params: Dict[str, Any] = {}
        # multi-agent (empty = single-agent)
        self.policies: list = []
        self.policy_mapping_fn = lambda agent_id: agent_id

    # ------------------------------------------------------ fluent setters
    def environment(self, env: str) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 platform: Optional[str] = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if platform is not None:
            self.learner_platform = platform
        return self

    def training(self, **params) -> "AlgorithmConfig":
        self.training_params.update(params)
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None
                    ) -> "AlgorithmConfig":
        """Per-agent policy mapping (reference:
        algorithm_config.py multi_agent() — policies + policy_mapping_fn).
        ``policies`` is an iterable of policy ids; ``policy_mapping_fn``
        maps agent_id -> policy id (default: identity)."""
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        if self.env is None:
            raise ValueError("config.environment(env_name) is required")
        return self.algo_class(self)

    @property
    def algo_class(self):
        raise NotImplementedError


def build_module_spec(config: "AlgorithmConfig") -> Dict[str, Any]:
    """Probe the env once and derive the policy-module spec (shared by every
    algorithm; reference: catalog/module-spec derivation)."""
    from ray_tpu.rllib.env import make_vector_env

    probe = make_vector_env(config.env, 1, seed=0)
    return {
        "observation_size": probe.observation_size,
        "num_actions": probe.num_actions,
        "hidden": tuple(config.model.get("hidden", (64, 64))),
    }


def build_runner_actors(config: "AlgorithmConfig", runner_cls,
                        runner_kwargs: Dict[str, Any],
                        index_key: Optional[str] = None) -> list:
    """Spawn a runner actor gang of any runner class (reference:
    EnvRunnerGroup) — one CPU each, per-runner decorrelated seeds.  With
    ``index_key`` each runner also receives its gang index under that
    kwarg (streaming consumers and chaos points address runners by it)."""
    import ray_tpu

    remote_cls = ray_tpu.remote(runner_cls)
    out = []
    for i in range(config.num_env_runners):
        kw = {**runner_kwargs,
              "seed": runner_kwargs.get("seed", 0) + 1000 * (i + 1)}
        if index_key is not None:
            kw[index_key] = i
        out.append(remote_cls.options(num_cpus=1).remote(**kw))
    return out


class Algorithm:
    """reference: rllib/algorithms/algorithm.py:227 (step :896)."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._start_time = time.monotonic()
        self.setup(config)

    # subclasses override
    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Trainable.train → step :896)."""
        self.iteration += 1
        results = self.training_step()
        results.setdefault("training_iteration", self.iteration)
        results.setdefault("time_total_s",
                           time.monotonic() - self._start_time)
        return results

    def stop(self) -> None:
        pass
