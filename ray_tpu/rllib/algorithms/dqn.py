"""DQN: off-policy Q-learning with a replay buffer and target network.

Counterpart of the reference's DQN (reference: rllib/algorithms/dqn/dqn.py —
DQNConfig with replay buffer config, target_network_update_freq,
epsilon schedule; loss in rllib/algorithms/dqn/torch/dqn_torch_learner.py —
double-Q + huber).  This is the control flow neither PPO nor IMPALA touches:
a PERSISTENT learner-local replay buffer, off-policy ratios >> 1 (each
transition is replayed many times), and a lagged target network synced on an
env-step schedule.

JAX-first layout: the buffer is host-side numpy ring storage (cheap gather on
sample; device memory holds only the current batch), and one jitted update
runs the double-DQN TD loss + adam over a scan of minibatches — U updates
per call in a single dispatch, no per-update host round-trip.  Exploration
(epsilon-greedy) runs on the CPU env-runner exactly like the other
algorithms (SURVEY §3.5: runners are host programs).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.rl_module import QModule


class ReplayBuffer:
    """Uniform circular transition store (reference:
    rllib/utils/replay_buffers/replay_buffer.py — storage ring +
    sample(num_items); prioritized variant left to a later round)."""

    def __init__(self, capacity: int, observation_size: int, seed: int = 0,
                 action_shape: tuple = (), action_dtype=np.int32):
        self.capacity = int(capacity)
        self.obs = np.empty((capacity, observation_size), np.float32)
        self.next_obs = np.empty((capacity, observation_size), np.float32)
        self.actions = np.empty((capacity,) + tuple(action_shape),
                                action_dtype)
        self.rewards = np.empty((capacity,), np.float32)
        self.discounts = np.empty((capacity,), np.float32)
        self.dones = np.empty((capacity,), np.float32)
        self._write = 0
        self.size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, obs, actions, rewards, next_obs, discounts,
                  dones) -> None:
        n = len(actions)
        idx = (self._write + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.next_obs[idx] = next_obs
        self.discounts[idx] = discounts
        self.dones[idx] = dones
        self._write = int((self._write + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample_indices(self, num_batches: int, batch_size: int) -> np.ndarray:
        return self._rng.integers(0, self.size,
                                  (num_batches, batch_size))

    def gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {"obs": self.obs[idx], "actions": self.actions[idx],
                "rewards": self.rewards[idx], "next_obs": self.next_obs[idx],
                "discounts": self.discounts[idx], "dones": self.dones[idx]}


class QEnvRunner:
    """Epsilon-greedy n-step transition sampler over K vectorized envs.

    Distinct from the on-policy EnvRunner: off-policy learning needs
    (s, a, R_n, s'', discount, done) transitions, where R_n is the n-step
    discounted reward sum, s'' the state n steps ahead (pre-reset
    ``final_obs`` at episode ends), and ``discount`` the γ^len bootstrap
    multiplier — episode-end flushes emit shorter windows, so the discount
    rides the transition instead of being a learner constant (reference:
    n_step handling in rllib/utils/replay_buffers + DQN loss's gamma**n_step).
    done means TERMINATED only — bootstrapping continues through time limits.
    """

    def __init__(self, env_name: str, num_envs: int, rollout_length: int,
                 module_spec: Dict, seed: int = 0, n_step: int = 3,
                 gamma: float = 0.99):
        import sys

        if "jax" in sys.modules:
            import jax._src.xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        else:
            initialized = False
        if not initialized:
            # pin rollout inference to CPU BEFORE the backend initializes
            # (see EnvRunner.__init__: un-pinned runners on a TPU VM
            # dispatch every per-step inference to the chip, ~270x slower)
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax

        from ray_tpu.rllib.env import make_vector_env

        self.env = make_vector_env(env_name, num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self.module = QModule(**module_spec)
        self.params = None
        self._rng = np.random.default_rng(seed + 7)
        self._greedy = jax.jit(self.module.forward_inference)
        self.obs = self.env.reset()
        import collections

        # per-env window of up to n pending (obs, action, [rewards...])
        self._pending = [collections.deque() for _ in range(num_envs)]
        self._ep_return = np.zeros(num_envs, np.float32)
        self._recent_returns: "collections.deque" = collections.deque(maxlen=100)
        self._lifetime_steps = 0

    def _emit(self, out, k, entry, succ_obs, done: bool):
        obs0, a0, rewards = entry
        ret = 0.0
        for r in reversed(rewards):
            ret = r + self.gamma * ret
        out["obs"].append(obs0)
        out["actions"].append(a0)
        out["rewards"].append(ret)
        out["next_obs"].append(succ_obs)
        out["discounts"].append(self.gamma ** len(rewards))
        out["dones"].append(1.0 if done else 0.0)

    def sample(self, weights=None, epsilon: float = 0.0) -> Dict[str, np.ndarray]:
        if weights is not None:
            self.params = weights
        assert self.params is not None
        T, K = self.rollout_length, self.num_envs
        out = {"obs": [], "actions": [], "rewards": [], "next_obs": [],
               "discounts": [], "dones": []}
        for t in range(T):
            greedy = np.asarray(self._greedy(self.params, self.obs))
            explore = self._rng.random(K) < epsilon
            actions = np.where(
                explore,
                self._rng.integers(0, self.env.num_actions, K),
                greedy).astype(np.int32)
            next_obs, rewards, terminated, truncated, info = \
                self.env.step(actions)
            done_any = terminated | truncated
            for k in range(K):
                pend = self._pending[k]
                pend.append((self.obs[k].copy(), int(actions[k]), []))
                for entry in pend:
                    entry[2].append(float(rewards[k]))
                if done_any[k]:
                    # flush every window; successor is the TRUE pre-reset
                    # state, done only when genuinely terminated
                    succ = info["final_obs"][k].copy()
                    while pend:
                        self._emit(out, k, pend.popleft(), succ,
                                   bool(terminated[k]))
                elif len(pend) == self.n_step:
                    self._emit(out, k, pend.popleft(), next_obs[k].copy(),
                               False)
            self._ep_return += rewards
            for i in np.nonzero(done_any)[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self.obs = next_obs
        self._lifetime_steps += T * K
        return {
            "obs": np.asarray(out["obs"], np.float32),
            "actions": np.asarray(out["actions"], np.int32),
            "rewards": np.asarray(out["rewards"], np.float32),
            "next_obs": np.asarray(out["next_obs"], np.float32),
            "discounts": np.asarray(out["discounts"], np.float32),
            "dones": np.asarray(out["dones"], np.float32),
        }

    def get_metrics(self) -> Dict:
        return {
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "num_episodes": len(self._recent_returns),
            "num_env_steps_sampled_lifetime": self._lifetime_steps,
        }

    def ping(self) -> bool:
        return True


def _dqn_update(module, tx, params, target_params, opt_state, batches, *,
                double_q, tau, use_huber=True):
    """U minibatch updates under ONE jit: lax.scan over stacked batches
    (reference loss: dqn_torch_learner.py compute_loss_for_module —
    double-Q action selection by the online net, evaluation by the target
    net, huber TD error)."""
    import jax
    import jax.numpy as jnp
    import optax

    def td_loss(p, target_params, mb):
        q = module.q_values(p, mb["obs"])
        q_a = jnp.take_along_axis(
            q, mb["actions"][..., None].astype(jnp.int32), -1)[..., 0]
        q_next_target = module.q_values(target_params, mb["next_obs"])
        if double_q:
            sel = jnp.argmax(module.q_values(p, mb["next_obs"]), axis=-1)
            q_next = jnp.take_along_axis(
                q_next_target, sel[..., None], -1)[..., 0]
        else:
            q_next = q_next_target.max(axis=-1)
        # discounts = gamma^n of each transition's window (n-step returns;
        # shorter windows at episode ends carry their own multiplier)
        target = mb["rewards"] + mb["discounts"] * (1.0 - mb["dones"]) \
            * jax.lax.stop_gradient(q_next)
        err = q_a - target
        loss = optax.huber_loss(q_a, target).mean() if use_huber \
            else 0.5 * jnp.square(q_a - target).mean()
        return loss, {"td_error_mean": jnp.abs(err).mean(),
                      "q_mean": q_a.mean()}

    def body(carry, mb):
        p, tp, s = carry
        (loss, stats), grads = jax.value_and_grad(
            lambda pp: td_loss(pp, tp, mb), has_aux=True)(p)
        updates, s = tx.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        # Polyak-averaged target (reference: tau config in DQNConfig);
        # tau=0 -> hard syncs handled by the caller on a step schedule
        tp = jax.tree_util.tree_map(
            lambda t, o: (1.0 - tau) * t + tau * o, tp, p) if tau > 0 else tp
        return (p, tp, s), {**stats, "total_loss": loss}

    (params, target_params, opt_state), stats = jax.lax.scan(
        body, (params, target_params, opt_state), batches)
    return params, target_params, opt_state, jax.tree_util.tree_map(
        lambda x: x[-1], stats)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_envs_per_env_runner = 16
        self.rollout_fragment_length = 8
        self.training_params = {
            "lr": 2.5e-4,
            "gamma": 0.99,
            "buffer_size": 50_000,
            "train_batch_size": 128,
            "num_updates_per_iter": 13,
            # tau=0 -> hard target sync every target_network_update_freq
            # env steps (the empirically stable default here); tau>0 ->
            # per-update Polyak averaging
            "tau": 0.0,
            "target_network_update_freq": 500,
            "learning_starts": 10_000,
            "epsilon_initial": 1.0,
            "epsilon_final": 0.05,
            "epsilon_anneal_steps": 250_000,
            "double_q": True,
            "dueling": True,
            "n_step": 3,
            # MSE, not huber: with huber's capped gradients the few
            # high-error grounded (terminal) samples cannot outweigh the
            # many slightly-inflating bootstrapped ones, and Q runs away;
            # MSE's error-proportional pull self-corrects (measured: huber
            # diverged to Q~1e7 on CartPole, MSE solves in ~200k steps)
            "use_huber": False,
            "grad_clip": 40.0,
        }

    @property
    def algo_class(self):
        return DQN


class DQN(Algorithm):
    def setup(self, config: DQNConfig) -> None:
        import jax
        import optax

        from ray_tpu.rllib.algorithms.algorithm import build_module_spec

        if config.learner_platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        spec = build_module_spec(config)
        p = config.training_params
        self.module = QModule(observation_size=spec["observation_size"],
                              num_actions=spec["num_actions"],
                              hidden=spec["hidden"],
                              dueling=p.get("dueling", True))
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        # jax arrays are immutable: sharing the pytree IS the snapshot
        self.target_params = self.params
        self.tx = optax.chain(
            optax.clip_by_global_norm(p["grad_clip"]),
            optax.adam(p["lr"]))
        self.opt_state = self.tx.init(self.params)
        self._update = jax.jit(functools.partial(
            _dqn_update, self.module, self.tx, double_q=p["double_q"],
            tau=p["tau"], use_huber=p.get("use_huber", True)))

        self.buffer = ReplayBuffer(p["buffer_size"],
                                   spec["observation_size"],
                                   seed=config.seed)
        self._steps_sampled = 0
        self._last_target_sync = 0

        self._runner_actors = []
        self._local_runner = None
        runner_kwargs = dict(
            env_name=config.env, num_envs=config.num_envs_per_env_runner,
            rollout_length=config.rollout_fragment_length,
            module_spec={**spec, "dueling": p.get("dueling", True)},
            seed=config.seed,
            n_step=p.get("n_step", 3), gamma=p["gamma"])
        if config.num_env_runners <= 0:
            self._local_runner = QEnvRunner(**runner_kwargs)
        else:
            from ray_tpu.rllib.algorithms.algorithm import build_runner_actors

            self._runner_actors = build_runner_actors(
                config, QEnvRunner, runner_kwargs)

    def _epsilon(self) -> float:
        p = self.config.training_params
        frac = min(self._steps_sampled / max(p["epsilon_anneal_steps"], 1),
                   1.0)
        return float(p["epsilon_initial"]
                     + frac * (p["epsilon_final"] - p["epsilon_initial"]))

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        p = self.config.training_params
        eps = self._epsilon()
        if self._local_runner is not None:
            batches = [self._local_runner.sample(self.params, eps)]
            metrics = [self._local_runner.get_metrics()]
        else:
            wref = ray_tpu.put(self.params)
            batches = ray_tpu.get([r.sample.remote(wref, eps)
                                   for r in self._runner_actors])
            metrics = ray_tpu.get([r.get_metrics.remote()
                                   for r in self._runner_actors])
        frag = self.config.rollout_fragment_length \
            * self.config.num_envs_per_env_runner
        for b in batches:
            self.buffer.add_batch(b["obs"], b["actions"], b["rewards"],
                                  b["next_obs"], b["discounts"], b["dones"])
            self._steps_sampled += frag

        stats: Dict[str, Any] = {}
        if self._steps_sampled >= p["learning_starts"]:
            idx = self.buffer.sample_indices(p["num_updates_per_iter"],
                                             p["train_batch_size"])
            stacked = self.buffer.gather(idx)  # (U, B, ...)
            self.params, self.target_params, self.opt_state, jstats = \
                self._update(self.params, self.target_params,
                             self.opt_state, stacked)
            stats = {k: float(v) for k, v in jstats.items()}
            if p["tau"] == 0 and self._steps_sampled - self._last_target_sync \
                    >= p.get("target_network_update_freq", 500):
                self.target_params = self.params
                self._last_target_sync = self._steps_sampled

        returns = [m["episode_return_mean"] for m in metrics
                   if np.isfinite(m["episode_return_mean"])]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._steps_sampled,
            "num_episodes": int(sum(m["num_episodes"] for m in metrics)),
            "epsilon": eps,
            "replay_buffer_size": self.buffer.size,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        for r in self._runner_actors:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._runner_actors = []
