"""JaxLearner + LearnerGroup: the device-side update program.

Counterpart of the reference's Learner stack (reference:
rllib/core/learner/learner.py:116, torch_learner.py:61 compute/apply
gradients :146,158, learner_group.py:83).  JAX-first redesign: the whole
update — GAE (associative scan), minibatch epochs (lax.scan over shuffled
minibatches), PPO loss, adam — is ONE jitted function; there is no
per-minibatch Python loop or host↔device ping-pong.  On TPU the same jit
runs on-chip; EnvRunners stay numpy/CPU (SURVEY §3.5).

LearnerGroup: local mode (learner in-driver, the default for one device) or
actor mode (Learner actors, weights synced via the object store).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import DiscretePolicyModule


class JaxLearner:
    def __init__(self, module_spec: Dict, config: Dict, seed: int = 0,
                 platform: Optional[str] = None):
        # platform="cpu" pins the learner off the accelerator (tests, or
        # CPU-only clusters); None keeps the process default (TPU on chips).
        if platform == "cpu":
            from ray_tpu._private.platform import force_cpu_platform

            force_cpu_platform(1)
        import jax
        import optax

        self.module = DiscretePolicyModule(**module_spec)
        self.config = dict(config)
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 0.5)),
            optax.adam(self.config.get("lr", 3e-4)),
        )
        self.opt_state = self.tx.init(self.params)
        self._key = jax.random.PRNGKey(seed + 1)
        self._update = jax.jit(functools.partial(
            _ppo_update, self.module, self.tx,
            num_epochs=self.config.get("num_epochs", 6),
            minibatch_size=self.config.get("minibatch_size", 256),
            clip_param=self.config.get("clip_param", 0.2),
            vf_loss_coeff=self.config.get("vf_loss_coeff", 0.5),
            entropy_coeff=self.config.get("entropy_coeff", 0.0),
            vf_clip_param=self.config.get("vf_clip_param", 10.0),
            gamma=self.config.get("gamma", 0.99),
            gae_lambda=self.config.get("gae_lambda", 0.95),
        ))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """batch: time-major fragments (T, K, ...) concatenated over runners
        along K, with next_values precomputed by the runners."""
        import jax

        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch, sub)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> None:
        self.params = params


def _ppo_update(module, tx, params, opt_state, batch, key, *,
                num_epochs, minibatch_size, clip_param, vf_loss_coeff,
                entropy_coeff, vf_clip_param, gamma, gae_lambda):
    """Whole PPO update under one jit (reference math:
    rllib/algorithms/ppo/torch/ppo_torch_learner.py compute_loss_for_module)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.ops.gae import gae_from_fragments

    dones = batch["terminated"] | batch["truncated"]
    adv, targets = gae_from_fragments(
        batch["rewards"], batch["values"], batch["next_values"],
        dones, gamma, gae_lambda)

    n = batch["rewards"].size
    flat = {
        "obs": batch["obs"].reshape(n, -1),
        "actions": batch["actions"].reshape(n),
        "logp_old": batch["logp"].reshape(n),
        "adv": adv.reshape(n),
        "targets": targets.reshape(n),
        "values_old": batch["values"].reshape(n),
    }
    minibatch_size = min(minibatch_size, n)
    num_minibatches = max(n // minibatch_size, 1)
    used = num_minibatches * minibatch_size

    def loss_fn(p, mb):
        logp, entropy = module.logp_entropy(p, mb["obs"], mb["actions"])
        ratio = jnp.exp(logp - mb["logp_old"])
        a = mb["adv"]
        a = (a - a.mean()) / (a.std() + 1e-8)  # per-minibatch adv norm
        surrogate = jnp.minimum(
            a * ratio, a * jnp.clip(ratio, 1 - clip_param, 1 + clip_param))
        v = module.value(p, mb["obs"])
        vf_err = jnp.clip((v - mb["targets"]) ** 2, 0.0, vf_clip_param)
        loss = (-surrogate.mean() + vf_loss_coeff * vf_err.mean()
                - entropy_coeff * entropy.mean())
        return loss, {
            "policy_loss": -surrogate.mean(),
            "vf_loss": vf_err.mean(),
            "entropy": entropy.mean(),
            "approx_kl": (mb["logp_old"] - logp).mean(),
        }

    def epoch_body(carry, epoch_key):
        p, s = carry
        perm = jax.random.permutation(epoch_key, n)[:used] \
            .reshape(num_minibatches, minibatch_size)

        def mb_body(carry, idx):
            p, s = carry
            mb = {k: v[idx] for k, v in flat.items()}
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p, mb)
            updates, s = tx.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), {**stats, "total_loss": loss}

        (p, s), stats = jax.lax.scan(mb_body, (p, s), perm)
        return (p, s), jax.tree_util.tree_map(jnp.mean, stats)

    keys = jax.random.split(key, num_epochs)
    (params, opt_state), stats = jax.lax.scan(
        epoch_body, (params, opt_state), keys)
    stats = jax.tree_util.tree_map(lambda x: x[-1], stats)  # last epoch
    stats["mean_advantage"] = adv.mean()
    stats["mean_value_target"] = targets.mean()
    return params, opt_state, stats


class LearnerGroup:
    """Weight owner + update dispatcher (reference:
    rllib/core/learner/learner_group.py:83).  num_learners=0 → local learner
    in the driver process (the reference's default for single-device)."""

    def __init__(self, module_spec: Dict, config: Dict, num_learners: int = 0,
                 seed: int = 0, platform: Optional[str] = None):
        self._local: Optional[JaxLearner] = None
        self._actors: List = []
        if num_learners <= 0:
            self._local = JaxLearner(module_spec, config, seed, platform)
        else:
            import ray_tpu

            learner_cls = ray_tpu.remote(JaxLearner)
            self._actors = [
                learner_cls.options(num_cpus=1).remote(module_spec, config,
                                                       seed + i, platform)
                for i in range(num_learners)
            ]

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu

        # shard the batch over learner actors along the env axis (K); each
        # learner updates independently and rank-0's weights win (single
        # learner is the common case; multi-learner grad sync arrives with
        # the collective-backed learner).  More actors than env columns →
        # the excess actors sit this round out (an empty shard would divide
        # by zero inside the update).
        k = batch["rewards"].shape[1]
        n_active = min(len(self._actors), k)
        per = k // n_active
        shards = []
        for i in range(n_active):
            sl = slice(i * per, (i + 1) * per if i < n_active - 1 else k)
            shards.append({key: v[:, sl] if v.ndim >= 2 else v
                           for key, v in batch.items()})
        stats = ray_tpu.get([a.update.remote(s)
                             for a, s in zip(self._actors, shards)])
        return stats[0]

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actors[0].get_weights.remote())

    def shutdown(self) -> None:
        import ray_tpu

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []
