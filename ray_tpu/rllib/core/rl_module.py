"""RLModule: the policy/value network as pure functions + a params pytree.

Counterpart of the reference's RLModule (reference:
rllib/core/rl_module/rl_module.py; default torch MLP in
rllib/core/models/torch/...).  JAX-first: the module is a (init, apply) pair
over an explicit params pytree — no stateful nn.Module — so the same
functions run inside the Learner's jitted update and inside the (CPU) env
runner's action computation.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _init_mlp(key, sizes: Sequence[int], final_scale: float = 1.0):
    """Tanh MLP params; final layer scaled down (policy heads want ~0 logits
    at init so early exploration is uniform)."""
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for i, k in enumerate(keys):
        fan_in = sizes[i]
        scale = final_scale if i == len(keys) - 1 else 1.0
        w = jax.random.normal(k, (sizes[i], sizes[i + 1])) \
            * scale / np.sqrt(fan_in)
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros((sizes[i + 1],), jnp.float32)})
    return params


def _apply_mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def _apply_relu_mlp(layers, x, final_relu: bool = False):
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1 or final_relu:
            x = jax.nn.relu(x)
    return x


class DiscretePolicyModule:
    """Separate policy/value tanh MLPs for discrete action spaces
    (reference default: vf_share_layers=False)."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict:
        kp, kv = jax.random.split(key)
        sizes_pi = (self.observation_size, *self.hidden, self.num_actions)
        sizes_vf = (self.observation_size, *self.hidden, 1)
        return {"pi": _init_mlp(kp, sizes_pi, final_scale=0.01),
                "vf": _init_mlp(kv, sizes_vf)}

    # --------------------------------------------------------- forwards
    def logits(self, params, obs) -> jnp.ndarray:
        return _apply_mlp(params["pi"], obs)

    def value(self, params, obs) -> jnp.ndarray:
        return _apply_mlp(params["vf"], obs)[..., 0]

    def forward_exploration(self, params, obs, key
                            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Sample actions; returns (actions, logp, values)."""
        logits = self.logits(params, obs)
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, actions[..., None], -1)[..., 0]
        return actions, logp_a, self.value(params, obs)

    def forward_inference(self, params, obs) -> jnp.ndarray:
        return jnp.argmax(self.logits(params, obs), axis=-1)

    def logp_entropy(self, params, obs, actions
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        logits = self.logits(params, obs)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, actions[..., None].astype(jnp.int32),
                                     -1)[..., 0]
        p = jnp.exp(logp)
        entropy = -jnp.sum(p * logp, axis=-1)
        return logp_a, entropy


class QModule:
    """Dueling Q-network for value-based algorithms (reference: DQN's
    catalog-built Q head, rllib/algorithms/dqn/torch/dqn_torch_rl_module.py
    compute_q_values; dueling decomposition Q = V + A - mean(A), the
    reference's `dueling=True` default for the tuned CartPole example).
    Relu trunk — value regression wants sharper features than tanh."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64), dueling: bool = True):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.dueling = dueling

    def init(self, key) -> Dict:
        if not self.dueling:
            sizes = (self.observation_size, *self.hidden, self.num_actions)
            return {"q": _init_mlp(key, sizes)}
        kt, ka, kv = jax.random.split(key, 3)
        trunk_sizes = (self.observation_size, *self.hidden)
        last = self.hidden[-1]
        return {"trunk": _init_mlp(kt, trunk_sizes),
                "adv": _init_mlp(ka, (last, self.num_actions)),
                "val": _init_mlp(kv, (last, 1))}

    _relu_mlp = staticmethod(_apply_relu_mlp)

    def q_values(self, params, obs) -> jnp.ndarray:
        if not self.dueling:
            return self._relu_mlp(params["q"], obs, final_relu=False)
        h = self._relu_mlp(params["trunk"], obs, final_relu=True)
        adv = self._relu_mlp(params["adv"], h, final_relu=False)
        val = self._relu_mlp(params["val"], h, final_relu=False)
        return val + adv - adv.mean(axis=-1, keepdims=True)

    def forward_inference(self, params, obs) -> jnp.ndarray:
        return jnp.argmax(self.q_values(params, obs), axis=-1)


class SquashedGaussianModule:
    """Continuous-control actor: tanh-squashed Gaussian policy (the SAC
    actor; reference: rllib's SACTorchRLModule action dist
    TorchSquashedGaussian).  ``sample`` returns (action, logp) with the
    tanh change-of-variables correction; actions scale to [-max_action,
    max_action]."""

    LOG_STD_MIN = -10.0
    LOG_STD_MAX = 2.0

    def __init__(self, observation_size: int, action_size: int,
                 max_action: float = 1.0, hidden: Sequence[int] = (64, 64)):
        self.observation_size = observation_size
        self.action_size = action_size
        self.max_action = float(max_action)
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict:
        sizes = (self.observation_size, *self.hidden, 2 * self.action_size)
        return {"pi": _init_mlp(key, sizes, final_scale=0.01)}

    def _dist(self, params, obs):
        out = _apply_mlp(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample(self, params, obs, key) -> Tuple[jnp.ndarray, jnp.ndarray]:
        mean, log_std = self._dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        # log N(pre) - log |d tanh/d pre| (numerically stable softplus form)
        logp = (-0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
                ).sum(axis=-1)
        logp -= (2.0 * (jnp.log(2.0) - pre
                        - jax.nn.softplus(-2.0 * pre))).sum(axis=-1)
        # scaling by max_action is part of the bijector: its Jacobian
        # contributes -sum(log max_action) to the density of the action
        logp -= self.action_size * jnp.log(self.max_action)
        return act * self.max_action, logp

    def forward_inference(self, params, obs) -> jnp.ndarray:
        mean, _ = self._dist(params, obs)
        return jnp.tanh(mean) * self.max_action


class TwinQModule:
    """Twin continuous Q(s, a) critics (clipped double-Q; reference: SAC's
    twin_q=True default)."""

    def __init__(self, observation_size: int, action_size: int,
                 hidden: Sequence[int] = (64, 64)):
        sizes = (observation_size + action_size, *hidden, 1)
        self._sizes = sizes

    def init(self, key) -> Dict:
        k1, k2 = jax.random.split(key)
        return {"q1": _init_mlp(k1, self._sizes),
                "q2": _init_mlp(k2, self._sizes)}

    def q_values(self, params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = jnp.concatenate([obs, act], axis=-1)
        return (_apply_relu_mlp(params["q1"], x)[..., 0],
                _apply_relu_mlp(params["q2"], x)[..., 0])
