"""ray_tpu.rllib: reinforcement learning on the actor runtime.

Counterpart of the reference's RLlib new API stack (reference: rllib/ —
EnvRunner actors sample on CPU, a JAX Learner updates on device, the
Algorithm is the Tune-trainable driver loop).
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.offline import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.podracer import (FragmentStream, InferencePool,
                                    LearnerGang, PodracerLearner,
                                    WeightMailbox)

__all__ = ["Algorithm", "AlgorithmConfig", "BC", "BCConfig",
           "DQN", "DQNConfig", "FragmentStream", "IMPALA", "IMPALAConfig",
           "InferencePool", "LearnerGang", "MARWIL", "MARWILConfig",
           "PPO", "PPOConfig", "PodracerLearner", "SAC", "SACConfig",
           "WeightMailbox"]
