"""AIR glue: shared config/result dataclasses used by Train and Tune.

Counterpart of the reference's ``ray.air`` (reference: python/ray/air/config.py,
python/ray/air/result.py).
"""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result

__all__ = [
    "CheckpointConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "Result",
]
