"""Shared Train/Tune config dataclasses.

Counterpart of the reference's AIR configs (reference: python/ray/air/config.py —
ScalingConfig, RunConfig, FailureConfig, CheckpointConfig).  TPU-first deltas:
``use_tpu``/``tpus_per_worker`` instead of GPU knobs, and the default gang
strategy for multi-host TPU groups is STRICT_SPREAD (one jax process per host;
SURVEY §2.3 gang-scheduling row).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many training workers and what resources each gets.

    Reference: python/ray/air/config.py ScalingConfig (num_workers,
    use_gpu, resources_per_worker, placement_strategy).
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float = 0.0
    resources_per_worker: Optional[Dict[str, float]] = None
    # None resolves to STRICT_SPREAD for TPU gangs (one jax process per
    # host — two TPU processes packed on one host fight over the chips) and
    # PACK otherwise (reference default).
    placement_strategy: Optional[str] = None

    def __post_init__(self):
        if self.num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {self.num_workers}")
        if self.use_tpu and self.tpus_per_worker == 0.0:
            self.tpus_per_worker = 1.0
        if self.tpus_per_worker and not self.use_tpu:
            self.use_tpu = True  # the knobs imply each other
        if self.placement_strategy is None:
            self.placement_strategy = "STRICT_SPREAD" if self.use_tpu else "PACK"

    @property
    def _worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.tpus_per_worker:
            res["TPU"] = float(self.tpus_per_worker)
        return res


@dataclass
class FailureConfig:
    """Trial/run retry policy (reference: air/config.py FailureConfig).

    max_failures: retries after a worker-group or trial crash; 0 = fail fast,
    -1 = retry forever.
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Checkpoint retention (reference: air/config.py CheckpointConfig)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Run-level config: where results/checkpoints land and retry policy
    (reference: air/config.py RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    # Max seconds between report() calls before the run is declared dead.
    # Must cover the FIRST step's XLA compile (minutes on big TPU programs).
    worker_report_timeout_s: float = 1800.0

    def __post_init__(self):
        if self.storage_path is None:
            from ray_tpu._private.config import RayConfig

            self.storage_path = os.path.expanduser(
                os.environ.get("RAY_TPU_STORAGE_PATH")
                or RayConfig.storage_path)
