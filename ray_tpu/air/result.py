"""Result of a training/tuning run (reference: python/ray/air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None  # train.Checkpoint
    path: Optional[str] = None
    error: Optional[Exception] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[Tuple[Any, Dict[str, Any]]] = field(default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return self.metrics.get("config")
