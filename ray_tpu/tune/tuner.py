"""Tuner: concurrent fault-tolerant trial execution.

Counterpart of the reference's Tuner/TuneController (reference:
python/ray/tune/tuner.py:44, fit :344; tune/execution/tune_controller.py:68).
Redesign: each trial runs as a remote TASK in its own worker process — a
function trainable runs directly; a Trainer trainable becomes a nested trial
driver that builds its own gang-scheduled worker group (the reference's
trial-actor → BackendExecutor layering, collapsed by one level).  The
controller is an event loop over ``ray_tpu.wait`` with per-trial retry
bookkeeping (FailureConfig.max_failures).

Experiment state is snapshotted to <storage>/<name>/tuner_state.json after
every trial transition (reference: tune/execution/experiment_state.py:61).
"""

from __future__ import annotations

import copy
import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.air.config import RunConfig
from ray_tpu.train import storage as _storage
from ray_tpu.air.result import Result
from ray_tpu.exceptions import RayError
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    """reference: tune/tune_config.py."""

    num_samples: int = 1
    max_concurrent_trials: int = 2
    metric: Optional[str] = None
    mode: str = "max"
    trial_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    scheduler: Optional[Any] = None  # FIFOScheduler | ASHAScheduler | PBT
    # Model-based searcher (e.g. search.TPESearcher): suggests each trial's
    # config from completed results instead of sampling independently
    # (reference: tune/search/ searchers).
    search_alg: Optional[Any] = None

    def __post_init__(self):
        if self.mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")


@dataclass
class Trial:
    """reference: tune/experiment/trial.py (state machine subset)."""

    index: int
    config: Dict[str, Any]
    name: str
    status: str = "PENDING"  # PENDING | RUNNING | TERMINATED | ERROR
    num_failures: int = 0
    result: Optional[Result] = None
    error: Optional[str] = None


class ResultGrid:
    """reference: tune/result_grid.py."""

    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("pass metric= (or set TuneConfig.metric)")
        ok = [r for r in self._results
              if r.error is None and metric in r.metrics]
        if not ok:
            raise RuntimeError("no successful trial reported "
                               f"metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(ok, key=key) if mode == "max" else min(ok, key=key)

    def get_dataframe(self):
        rows = [dict(r.metrics, trial_path=r.path) for r in self._results
                if r.error is None]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows


def _run_function_trial(fn: Callable, config: Dict[str, Any],
                        trial_dir: str, coordinator=None,
                        trial_index: int = -1,
                        start_checkpoint=None) -> Dict[str, Any]:
    """Task body for a function trainable: returns its final metrics dict.
    Installs a tune session so ``tune.report`` streams intermediate metrics
    to the controller and cooperative early-stop works (ASHA/PBT)."""
    from ray_tpu.tune import session as tune_session

    _storage.makedirs(trial_dir)
    sess = None
    if coordinator is not None:
        sess = tune_session._TuneSession(coordinator, trial_index)
        sess.start_checkpoint = start_checkpoint
        tune_session._set_session(sess)
    try:
        out = fn(config)
    except tune_session.StopTrial:
        out = dict(sess.last_metrics or {})
        out["__early_stopped__"] = True
    finally:
        tune_session._set_session(None)
    if out is None:
        out = dict(sess.last_metrics or {}) if sess else {}
    if not isinstance(out, dict):
        raise TypeError(
            f"function trainable must return a metrics dict, got {type(out)}")
    return out


def _run_trainer_trial(trainer_blob: bytes, config: Dict[str, Any],
                       trial_name: str) -> Dict[str, Any]:
    """Task body for a Trainer trainable: this worker process becomes the
    trial driver — it deserializes the trainer, merges the trial config into
    train_loop_config, and runs fit() (which builds its own worker group)."""
    import cloudpickle

    trainer = cloudpickle.loads(trainer_blob)
    trainer.train_loop_config = {**trainer.train_loop_config, **config}
    trainer.run_config.name = trial_name
    result = trainer.fit()
    return {"_metrics": result.metrics, "_path": result.path,
            "_checkpoint": result.checkpoint.path if result.checkpoint else None,
            "_history": result.metrics_history}


class Tuner:
    def __init__(self, trainable: Union[Callable, Any], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = copy.deepcopy(run_config) if run_config else RunConfig()
        if self._run_config.name is None:
            self._run_config.name = \
                f"tune_{time.strftime('%Y-%m-%d_%H-%M-%S')}_{uuid.uuid4().hex[:6]}"

    # ------------------------------------------------------------------ fit
    def fit(self) -> ResultGrid:
        from ray_tpu.train.base_trainer import BaseTrainer

        is_trainer = isinstance(self._trainable, BaseTrainer)
        searcher = self._tune_config.search_alg
        if searcher is not None:
            # model-based search: configs are suggested lazily at submit
            # time so each suggestion sees every completed result
            searcher.setup(self._param_space, self._tune_config.metric,
                           self._tune_config.mode)
            variants = [None] * max(self._tune_config.num_samples, 1)
        else:
            variants = generate_variants(self._param_space,
                                         self._tune_config.num_samples)
        exp_dir = _storage.join(
            _storage.expand(self._run_config.storage_path),
            self._run_config.name)
        _storage.makedirs(exp_dir)
        trials = [
            Trial(index=i, config=v, name=f"trial_{i:05d}")
            for i, v in enumerate(variants)
        ]

        if is_trainer:
            import cloudpickle

            base = copy.deepcopy(self._trainable)
            base.run_config = copy.deepcopy(self._run_config)
            base.run_config.storage_path = exp_dir
            # per-trial retries happen inside the nested fit(); the
            # controller-level retry below handles process/node loss
            trainer_blob = cloudpickle.dumps(base)

        max_failures = self._run_config.failure_config.max_failures
        remote_opts = {"num_cpus":
                       self._tune_config.trial_resources.get("CPU", 1.0),
                       "max_retries": 0}
        extra = {k: v for k, v in self._tune_config.trial_resources.items()
                 if k != "CPU"}
        if extra:
            remote_opts["resources"] = extra

        fn_task = ray_tpu.remote(_run_function_trial).options(**remote_opts)
        tr_task = ray_tpu.remote(_run_trainer_trial).options(**remote_opts)

        # Scheduler + intermediate-result channel (reference: TuneController
        # feeding its TrialScheduler; schedulers.py ASHA/PBT).
        from ray_tpu.tune._trial_coordinator import TrialCoordinator
        from ray_tpu.tune.schedulers import FIFOScheduler

        scheduler = self._tune_config.scheduler or FIFOScheduler()
        scheduler.set_experiment(self._tune_config.metric,
                                 self._tune_config.mode)
        # Plain FIFO needs no intermediate-result channel: skip the
        # coordinator actor (and its 0.5s polling) entirely.
        needs_coordinator = type(scheduler) is not FIFOScheduler \
            and not is_trainer
        coordinator = TrialCoordinator.remote() if needs_coordinator else None

        def submit(trial: Trial, start_checkpoint=None):
            trial.status = "RUNNING"
            if coordinator is not None:
                ray_tpu.get(coordinator.clear_trial.remote(trial.index),
                            timeout=60)
            if is_trainer:
                return tr_task.remote(trainer_blob, trial.config, trial.name)
            return fn_task.remote(self._trainable, trial.config,
                                  _storage.join(exp_dir, trial.name),
                                  coordinator, trial.index, start_checkpoint)

        by_index = {t.index: t for t in trials}

        def pump_scheduler():
            from ray_tpu.tune.schedulers import STOP, PopulationBasedTraining

            if coordinator is None:
                return
            for ev in ray_tpu.get(coordinator.drain.remote(), timeout=60):
                trial = by_index.get(ev["trial"])
                if trial is None or trial.status != "RUNNING":
                    continue
                if ev.get("checkpoint") is not None and \
                        isinstance(scheduler, PopulationBasedTraining):
                    scheduler.record_checkpoint(trial.index, ev["checkpoint"])
                if scheduler.on_result(trial, ev["metrics"]) == STOP:
                    ray_tpu.get(coordinator.set_stop.remote(trial.index),
                                timeout=60)

        pending = list(trials)
        running: Dict[Any, Trial] = {}
        wait_timeout = 0.5 if coordinator is not None else None
        try:
            return self._drive(trials, pending, running, submit,
                               pump_scheduler, scheduler, exp_dir, is_trainer,
                               max_failures, wait_timeout)
        finally:
            if coordinator is not None:
                try:
                    ray_tpu.kill(coordinator)
                except Exception:
                    pass

    def _drive(self, trials, pending, running, submit, pump_scheduler,
               scheduler, exp_dir, is_trainer, max_failures, wait_timeout):
        searcher = self._tune_config.search_alg
        while pending or running:
            while pending and len(running) < \
                    self._tune_config.max_concurrent_trials:
                t = pending.pop(0)
                if t.config is None:
                    t.config = searcher.suggest()
                ckpt = t.config.pop("__pbt_checkpoint__", None)
                running[submit(t, ckpt)] = t
            ready, _ = ray_tpu.wait(list(running), num_returns=1,
                                    timeout=wait_timeout)
            pump_scheduler()
            if not ready:
                continue
            ref = ready[0]
            trial = running.pop(ref)
            try:
                out = ray_tpu.get(ref)
            except (RayError, Exception) as e:  # noqa: B902
                trial.num_failures += 1
                if max_failures < 0 or trial.num_failures <= max_failures:
                    pending.append(trial)
                    trial.status = "PENDING"
                else:
                    trial.status = "ERROR"
                    trial.error = repr(e)
                    trial.result = Result(
                        metrics={"config": trial.config}, error=e,
                        path=_storage.join(exp_dir, trial.name))
                self._snapshot(exp_dir, trials)
                continue
            trial.status = "TERMINATED"
            if searcher is not None and isinstance(out, dict):
                final = out.get("_metrics", out) if is_trainer else out
                searcher.on_trial_complete(
                    trial.config, (final or {}).get(self._tune_config.metric))
            decision = scheduler.on_trial_complete(
                trial, out if isinstance(out, dict) else None)
            if decision is not None and decision[0] == "restart":
                trial.config = decision[1]
                trial.status = "PENDING"
                pending.append(trial)
                self._snapshot(exp_dir, trials)
                continue
            if is_trainer:
                from ray_tpu.train._checkpoint import Checkpoint

                trial.result = Result(
                    metrics={**out["_metrics"], "config": trial.config},
                    checkpoint=(Checkpoint(out["_checkpoint"])
                                if out["_checkpoint"] else None),
                    path=out["_path"],
                    metrics_history=out["_history"])
            else:
                trial.result = Result(
                    metrics={**out, "config": trial.config},
                    path=_storage.join(exp_dir, trial.name))
            self._snapshot(exp_dir, trials)

        return ResultGrid([t.result for t in trials],
                          self._tune_config.metric, self._tune_config.mode)

    def _snapshot(self, exp_dir: str, trials: List[Trial]) -> None:
        _storage.write_bytes(
            _storage.join(exp_dir, "tuner_state.json"),
            json.dumps({
                "time": time.time(),
                "trials": [{
                    "name": t.name, "status": t.status,
                    "num_failures": t.num_failures, "error": t.error,
                    # config is None until a model-based searcher suggests it
                    "config": {k: repr(v)
                               for k, v in (t.config or {}).items()},
                } for t in trials],
            }, indent=2).encode())
