"""ray_tpu.tune: experiment running (reference: python/ray/tune/)."""

from ray_tpu.tune._single_trial import run_trainer_as_single_trial

__all__ = ["run_trainer_as_single_trial"]
