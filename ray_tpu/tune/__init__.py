"""ray_tpu.tune: experiment running (reference: python/ray/tune/)."""

from ray_tpu.tune._single_trial import run_trainer_as_single_trial
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     PopulationBasedTraining)
from ray_tpu.tune.session import get_checkpoint, report
from ray_tpu.tune.search import TPESearcher
from ray_tpu.tune.tuner import ResultGrid, Trial, TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "Trial", "ResultGrid", "TPESearcher",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "run_trainer_as_single_trial", "report", "get_checkpoint",
    "FIFOScheduler", "ASHAScheduler", "PopulationBasedTraining",
]
