"""Trial schedulers: FIFO, ASHA early stopping, Population Based Training.

Reference: python/ray/tune/schedulers/ — async_hyperband.py (ASHA), pbt.py
(PopulationBasedTraining), FIFOScheduler.  Same decision surface, condensed:
``on_result(trial, metrics) -> "continue" | "stop"`` for intermediate
results, ``on_trial_complete(trial, metrics) -> None | ("restart", config)``
for PBT exploit/explore restarts.
"""

from __future__ import annotations

import copy
import math
import random
from typing import Any, Dict, List, Optional, Tuple

CONTINUE = "continue"
STOP = "stop"


class FIFOScheduler:
    """No early stopping (reference: FIFOScheduler — the default)."""

    def set_experiment(self, metric: Optional[str], mode: str) -> None:
        self.metric = metric
        self.mode = mode

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, metrics: Optional[Dict[str, Any]]):
        return None


class ASHAScheduler(FIFOScheduler):
    """Asynchronous Successive Halving (reference: schedulers/
    async_hyperband.py AsyncHyperBandScheduler).

    Rungs at grace_period * reduction_factor^k.  When a trial reaches a rung,
    it continues only if its metric is in the top 1/reduction_factor of
    results recorded AT that rung so far — asynchronous: no waiting for a
    full bracket.
    """

    def __init__(self, *, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self._rungs: Dict[int, List[float]] = {}
        rung = grace_period
        while rung < max_t:
            self._rungs[rung] = []
            rung = int(math.ceil(rung * reduction_factor))

    def set_experiment(self, metric, mode):
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        t = metrics.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value)
        if self.mode == "min":
            value = -value
        for rung in sorted(self._rungs, reverse=True):
            if t == rung:
                recorded = self._rungs[rung]
                recorded.append(value)
                if len(recorded) > 1:
                    k = max(1, int(len(recorded) / self.rf))
                    cutoff = sorted(recorded, reverse=True)[k - 1]
                    if value < cutoff:
                        return STOP
                break
        return CONTINUE


class PopulationBasedTraining(FIFOScheduler):
    """PBT (reference: schedulers/pbt.py).  At each perturbation interval a
    bottom-quantile trial is stopped and RESTARTED with a top-quantile
    trial's config (exploit), perturbed (explore); the donor's latest
    checkpoint rides along in config["__pbt_checkpoint__"] so the restarted
    trial can warm-start."""

    def __init__(self, *, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 perturb_factors: Tuple[float, float] = (0.8, 1.2),
                 seed: Optional[int] = None,
                 time_attr: str = "training_iteration",
                 max_exploits_per_trial: int = 4):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.perturb_factors = perturb_factors
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._latest: Dict[int, Dict[str, Any]] = {}   # trial idx -> metrics
        self._configs: Dict[int, Dict[str, Any]] = {}
        self._checkpoints: Dict[int, Any] = {}
        self._restarts: Dict[int, Dict[str, Any]] = {}  # planned restarts
        # Our restarts re-run the trainable from its (warm-started) top, so
        # unlike the reference (which continues cumulative iterations from a
        # checkpoint) an unbounded exploit loop would never converge: budget
        # the exploits per trial.
        self.max_exploits = max_exploits_per_trial
        self._exploit_counts: Dict[int, int] = {}

    def set_experiment(self, metric, mode):
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def _score(self, metrics) -> Optional[float]:
        v = metrics.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def record_checkpoint(self, trial_index: int, checkpoint) -> None:
        self._checkpoints[trial_index] = checkpoint

    def on_result(self, trial, metrics: Dict[str, Any]) -> str:
        self._latest[trial.index] = metrics
        self._configs[trial.index] = trial.config
        t = metrics.get(self.time_attr, 0)
        if t == 0 or t % self.interval:
            return CONTINUE
        scored = [(idx, self._score(m)) for idx, m in self._latest.items()]
        scored = [(i, sc) for i, sc in scored if sc is not None]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda x: x[1])
        n_q = max(1, int(len(scored) * self.quantile))
        bottom = {i for i, _ in scored[:n_q]}
        top = [i for i, _ in scored[-n_q:]]
        if trial.index not in bottom or trial.index in top:
            return CONTINUE
        if self._exploit_counts.get(trial.index, 0) >= self.max_exploits:
            return CONTINUE
        self._exploit_counts[trial.index] = \
            self._exploit_counts.get(trial.index, 0) + 1
        donor = self._rng.choice(top)
        new_config = self._explore(copy.deepcopy(self._configs.get(
            donor, trial.config)))
        if donor in self._checkpoints:
            new_config["__pbt_checkpoint__"] = self._checkpoints[donor]
        self._restarts[trial.index] = new_config
        return STOP

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if callable(spec):
                config[key] = spec()
            elif isinstance(spec, (list, tuple)):
                config[key] = self._rng.choice(list(spec))
            elif isinstance(config[key], (int, float)):
                factor = self._rng.choice(self.perturb_factors)
                config[key] = type(config[key])(config[key] * factor)
        return config

    def on_trial_complete(self, trial, metrics):
        new_config = self._restarts.pop(trial.index, None)
        if new_config is not None:
            return ("restart", new_config)
        return None
