"""TrialCoordinator: the intermediate-result channel between running trials
and the Tune controller.

Reference: the reference routes intermediate results trial-actor ->
TuneController over actor futures (tune_controller.py:68); here trials are
TASKS, so reporting flows through this small actor instead: trials push
metrics (and learn whether to stop), the controller drains the stream and
feeds its scheduler.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class TrialCoordinator:
    def __init__(self):
        self._events: List[dict] = []
        self._stopped: set = set()
        self._iters: Dict[int, int] = {}
        self._checkpoints: Dict[int, Any] = {}

    def report(self, trial_index: int, metrics: Dict[str, Any],
               checkpoint: Optional[str] = None) -> bool:
        """Called from inside a trial; returns True when the scheduler asked
        this trial to stop."""
        it = self._iters.get(trial_index, 0) + 1
        self._iters[trial_index] = it
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", it)
        if checkpoint is not None:
            self._checkpoints[trial_index] = checkpoint
        self._events.append({"trial": trial_index, "metrics": metrics,
                             "checkpoint": checkpoint})
        return trial_index in self._stopped

    def drain(self) -> List[dict]:
        events, self._events = self._events, []
        return events

    def set_stop(self, trial_index: int) -> None:
        self._stopped.add(trial_index)

    def clear_trial(self, trial_index: int) -> None:
        """A restarted trial starts a fresh iteration counter and stop flag."""
        self._stopped.discard(trial_index)
        self._iters.pop(trial_index, None)

    def latest_checkpoint(self, trial_index: int):
        return self._checkpoints.get(trial_index)
