"""Single-trial execution with failure retries.

The reference wraps every ``Trainer.fit()`` in a one-trial Tuner
(reference: python/ray/train/base_trainer.py:577-623 → tune/tuner.py:344 →
tune/execution/tune_controller.py:68).  This module is that path's core:
run one trainable, and on failure restart it from the latest durable
checkpoint up to FailureConfig.max_failures times.  The full Tuner drives
many of these concurrently.
"""

from __future__ import annotations

import logging

from ray_tpu.air.result import Result
from ray_tpu.exceptions import RayError
from ray_tpu.train._backend_executor import TrainingFailedError

logger = logging.getLogger(__name__)

# What a retry can plausibly fix: a worker crash mid-loop
# (TrainingFailedError), a node/actor loss during worker-group bring-up
# (RayError), or the gang not being schedulable yet (TimeoutError).
_RETRYABLE = (TrainingFailedError, RayError, TimeoutError)


def run_trainer_as_single_trial(trainer) -> Result:
    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train.base_trainer import latest_checkpoint

    max_failures = trainer.run_config.failure_config.max_failures
    attempt = 0
    while True:
        try:
            return trainer.training_loop()
        except _RETRYABLE as e:
            attempt += 1
            if max_failures >= 0 and attempt > max_failures:
                raise
            latest = latest_checkpoint(trainer.trial_dir)
            logger.warning(
                "trial %s failed (attempt %d/%s): %s — restarting from %s",
                trainer.run_config.name, attempt,
                max_failures if max_failures >= 0 else "inf", e,
                latest or "scratch")
            if latest:
                trainer.resume_from_checkpoint = Checkpoint(latest)
