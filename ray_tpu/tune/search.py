"""Search-space primitives + the basic variant generator.

Counterpart of the reference's search space API (reference:
python/ray/tune/search/sample.py — tune.grid_search/choice/uniform;
variant generation tune/search/basic_variant.py).  Minimal but same shapes:
grid_search expands cartesian; samplers draw per num_samples.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, List


class _GridSearch:
    def __init__(self, values):
        self.values = list(values)


class _Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class _Choice(_Sampler):
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class _Uniform(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class _LogUniform(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class _Randint(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


def grid_search(values) -> _GridSearch:
    return _GridSearch(values)


def choice(values) -> _Choice:
    return _Choice(values)


def uniform(low: float, high: float) -> _Uniform:
    return _Uniform(low, high)


def loguniform(low: float, high: float) -> _LogUniform:
    return _LogUniform(low, high)


def randint(low: int, high: int) -> _Randint:
    return _Randint(low, high)


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Expand a param space into concrete trial configs: the cartesian
    product of every grid_search, repeated num_samples times with samplers
    re-drawn each repeat (reference: basic_variant.py semantics)."""
    rng = random.Random(seed)

    grid_keys: List[str] = []
    grid_values: List[List[Any]] = []

    def find_grids(space, prefix=""):
        for k, v in space.items():
            path = f"{prefix}{k}"
            if isinstance(v, _GridSearch):
                grid_keys.append(path)
                grid_values.append(v.values)
            elif isinstance(v, dict):
                find_grids(v, f"{path}/")

    find_grids(param_space)

    def materialize(space, assignment, prefix=""):
        out = {}
        for k, v in space.items():
            path = f"{prefix}{k}"
            if isinstance(v, _GridSearch):
                out[k] = assignment[path]
            elif isinstance(v, _Sampler):
                out[k] = v.sample(rng)
            elif isinstance(v, dict):
                out[k] = materialize(v, assignment, f"{path}/")
            else:
                out[k] = v
        return out

    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(max(num_samples, 1)):
        for combo in combos:
            assignment = dict(zip(grid_keys, combo))
            variants.append(materialize(param_space, assignment))
    return variants
