"""Search-space primitives + the basic variant generator.

Counterpart of the reference's search space API (reference:
python/ray/tune/search/sample.py — tune.grid_search/choice/uniform;
variant generation tune/search/basic_variant.py).  Minimal but same shapes:
grid_search expands cartesian; samplers draw per num_samples.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, List


class _GridSearch:
    def __init__(self, values):
        self.values = list(values)


class _Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class _Choice(_Sampler):
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class _Uniform(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class _LogUniform(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class _Randint(_Sampler):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


def grid_search(values) -> _GridSearch:
    return _GridSearch(values)


def choice(values) -> _Choice:
    return _Choice(values)


def uniform(low: float, high: float) -> _Uniform:
    return _Uniform(low, high)


def loguniform(low: float, high: float) -> _LogUniform:
    return _LogUniform(low, high)


def randint(low: int, high: int) -> _Randint:
    return _Randint(low, high)


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Expand a param space into concrete trial configs: the cartesian
    product of every grid_search, repeated num_samples times with samplers
    re-drawn each repeat (reference: basic_variant.py semantics)."""
    rng = random.Random(seed)

    grid_keys: List[str] = []
    grid_values: List[List[Any]] = []

    def find_grids(space, prefix=""):
        for k, v in space.items():
            path = f"{prefix}{k}"
            if isinstance(v, _GridSearch):
                grid_keys.append(path)
                grid_values.append(v.values)
            elif isinstance(v, dict):
                find_grids(v, f"{path}/")

    find_grids(param_space)

    def materialize(space, assignment, prefix=""):
        out = {}
        for k, v in space.items():
            path = f"{prefix}{k}"
            if isinstance(v, _GridSearch):
                out[k] = assignment[path]
            elif isinstance(v, _Sampler):
                out[k] = v.sample(rng)
            elif isinstance(v, dict):
                out[k] = materialize(v, assignment, f"{path}/")
            else:
                out[k] = v
        return out

    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(max(num_samples, 1)):
        for combo in combos:
            assignment = dict(zip(grid_keys, combo))
            variants.append(materialize(param_space, assignment))
    return variants


class TPESearcher:
    """Tree-structured Parzen Estimator — an OWN implementation, not a
    wrapper (the reference wraps hyperopt/optuna/bohb,
    python/ray/tune/search/).  Classic TPE: completed trials split into a
    good quantile and the rest; numeric params get Parzen (Gaussian-mixture)
    densities l(x) over the good points and g(x) over the bad; candidates
    are drawn from l and ranked by log l(x) - log g(x); categoricals use
    smoothed count ratios.  Until ``n_initial`` results exist it behaves as
    random search.

    Supports uniform/loguniform/randint/choice dimensions (grid_search is a
    basic-variant concept and is rejected).
    """

    def __init__(self, n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._space: Dict[str, Any] = {}
        self._metric: str = ""
        self._mode: str = "max"
        self._obs: List[Any] = []  # (score, flat_config)

    # ------------------------------------------------------------- set-up
    def setup(self, param_space: Dict[str, Any], metric: str,
              mode: str) -> None:
        self._metric = metric
        self._mode = mode
        self._space = {}
        # a fresh experiment must not inherit another run's observations
        self._obs = []

        def walk(space, prefix=""):
            for k, v in space.items():
                path = f"{prefix}{k}"
                if isinstance(v, _GridSearch):
                    raise ValueError(
                        "TPESearcher does not accept grid_search dimensions; "
                        "use choice() instead")
                if isinstance(v, _Sampler):
                    self._space[path] = v
                elif isinstance(v, dict):
                    walk(v, f"{path}/")
                else:
                    self._space[path] = v  # constant

        walk(param_space)
        if metric is None:
            raise ValueError("TPESearcher needs TuneConfig(metric=...)")

    # ------------------------------------------------------------ suggest
    def suggest(self) -> Dict[str, Any]:
        if len(self._obs) < self.n_initial:
            flat = {k: (v.sample(self._rng) if isinstance(v, _Sampler) else v)
                    for k, v in self._space.items()}
            return self._unflatten(flat)
        good, bad = self._split()
        # per-dimension observation stats depend only on (good, bad): build
        # once, reuse across every candidate draw
        stats = {key: self._dim_stats(key, dim, good, bad)
                 for key, dim in self._space.items()
                 if isinstance(dim, _Sampler)}
        best_flat, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            flat, score = {}, 0.0
            for key, dim in self._space.items():
                if not isinstance(dim, _Sampler):
                    flat[key] = dim
                    continue
                value, ll = self._draw_dim(dim, stats[key])
                flat[key] = value
                score += ll
            if score > best_score:
                best_flat, best_score = flat, score
        return self._unflatten(best_flat)

    def on_trial_complete(self, config: Dict[str, Any], score) -> None:
        if score is None:
            return
        score = float(score)
        if not math.isfinite(score):
            # NaN/inf (diverged trials) would scramble the good/bad ranking
            # (NaN comparisons are always False) — drop them like hyperopt
            return
        if self._mode == "min":
            score = -score
        self._obs.append((score, self._flatten(config)))

    # ------------------------------------------------------------ internals
    def _split(self):
        ranked = sorted(self._obs, key=lambda o: -o[0])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:] or ranked[n_good - 1:]

    def _dim_values(self, obs, key, transform):
        return [transform(o[1][key]) for o in obs if key in o[1]]

    def _dim_stats(self, key, dim, good, bad):
        """Per-dimension modelling state shared by all candidate draws."""
        if isinstance(dim, _Choice):
            k = len(dim.values)
            g_counts = [1.0] * k  # +1 smoothing
            b_counts = [1.0] * k
            index = {self._cat_key(v): i for i, v in enumerate(dim.values)}
            for o in good:
                i = index.get(self._cat_key(o[1].get(key)))
                if i is not None:
                    g_counts[i] += 1
            for o in bad:
                i = index.get(self._cat_key(o[1].get(key)))
                if i is not None:
                    b_counts[i] += 1
            return ("cat", g_counts, b_counts)

        # numeric: uniform / loguniform / randint in (possibly log) space
        if isinstance(dim, _LogUniform):
            lo, hi = math.log(dim.low), math.log(dim.high)
        elif isinstance(dim, _Randint):
            lo, hi = float(dim.low), float(dim.high - 1)
        else:
            lo, hi = float(dim.low), float(dim.high)
        fwd = math.log if isinstance(dim, _LogUniform) else float
        span = max(hi - lo, 1e-12)
        g_vals = self._dim_values(good, key, fwd) or [lo + span / 2]
        b_vals = self._dim_values(bad, key, fwd) or [lo + span / 2]

        def bandwidth(vals):
            # Silverman over the GROUP's spread (tightens as the good points
            # cluster), floored at span/min(100, n+2) like hyperopt's
            # adaptive-Parzen minimum: without the floor the kernel collapses
            # onto an early local best and resamples the same point forever.
            n = len(vals)
            floor = span / min(100, n + 2)
            if n < 2:
                return span / 4
            mean = sum(vals) / n
            std = math.sqrt(sum((v - mean) ** 2 for v in vals) / (n - 1))
            return max(1.06 * std * n ** -0.2, floor)

        return ("num", lo, hi, span, g_vals, b_vals,
                bandwidth(g_vals), bandwidth(b_vals))

    def _draw_dim(self, dim, stats):
        if stats[0] == "cat":
            _, g_counts, b_counts = stats
            k = len(dim.values)
            g_tot = sum(g_counts)
            b_tot = sum(b_counts)
            # sample from the good distribution
            r = self._rng.random() * g_tot
            acc = 0.0
            pick = k - 1
            for i in range(k):
                acc += g_counts[i]
                if r <= acc:
                    pick = i
                    break
            ll = math.log(g_counts[pick] / g_tot) - \
                math.log(b_counts[pick] / b_tot)
            return dim.values[pick], ll

        _, lo, hi, span, g_vals, b_vals, g_sigma, b_sigma = stats
        if isinstance(dim, _Randint):
            def inv(x):
                return min(max(int(round(x)), dim.low), dim.high - 1)
        elif isinstance(dim, _LogUniform):
            inv = math.exp
        else:
            inv = float
        # Uniform prior kernel mixed into BOTH densities (hyperopt does the
        # same): without it the good-mixture collapses onto the early best
        # point and never explores again (premature convergence).
        prior = 1.0 / (len(g_vals) + 1)
        if self._rng.random() < prior:
            x = self._rng.uniform(lo, hi)
        else:
            center = self._rng.choice(g_vals)
            x = min(max(self._rng.gauss(center, g_sigma), lo), hi)

        def density(vals, sigma, p):
            return p / span + (1 - p) * self._parzen(x, vals, sigma)

        ll = math.log(density(g_vals, g_sigma, prior)) - \
            math.log(density(b_vals, b_sigma, 1.0 / (len(b_vals) + 1)))
        return inv(x), ll

    @staticmethod
    def _parzen(x, values, sigma):
        s = sum(math.exp(-0.5 * ((x - v) / sigma) ** 2) for v in values)
        return max(s / (len(values) * sigma * math.sqrt(2 * math.pi)), 1e-300)

    @staticmethod
    def _cat_key(v):
        try:
            hash(v)
            return v
        except TypeError:
            return repr(v)

    # flat "a/b" keys <-> nested dicts (matches generate_variants paths)
    def _flatten(self, config, prefix=""):
        out = {}
        for k, v in config.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(self._flatten(v, f"{path}/"))
            else:
                out[path] = v
        return out

    def _unflatten(self, flat):
        out: Dict[str, Any] = {}
        for path, v in flat.items():
            parts = path.split("/")
            node = out
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = v
        return out
