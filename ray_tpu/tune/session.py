"""tune.report: intermediate metric reporting from inside a trial.

Reference: ray.tune.report / ray.train.report (session.py:403).  The trial
task wrapper installs a session (coordinator handle + trial index); user code
calls ``tune.report(metrics, checkpoint=...)`` each iteration.  When the
scheduler has decided to stop this trial (ASHA rung cut, PBT exploit), the
NEXT report raises ``StopTrial``, which the wrapper treats as a graceful
early exit — cooperative stopping, same contract as reference trainables.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_tls = threading.local()


class StopTrial(Exception):
    """Raised inside a trial when the scheduler stops it early."""


class _TuneSession:
    def __init__(self, coordinator, trial_index: int):
        self.coordinator = coordinator
        self.trial_index = trial_index
        self.last_metrics: Optional[Dict[str, Any]] = None


def _set_session(session: Optional[_TuneSession]) -> None:
    _tls.session = session


def get_session() -> Optional[_TuneSession]:
    return getattr(_tls, "session", None)


def report(metrics: Dict[str, Any], *, checkpoint: Optional[str] = None) -> None:
    """Report one iteration's metrics (and optionally a checkpoint path).

    Outside a Tune trial this is a no-op, so the same training function runs
    standalone and under the Tuner unchanged (reference behavior).
    """
    import ray_tpu

    session = get_session()
    if session is None:
        return
    session.last_metrics = dict(metrics)
    should_stop = ray_tpu.get(
        session.coordinator.report.remote(
            session.trial_index, metrics, checkpoint),
        timeout=60)
    if should_stop:
        raise StopTrial()


def get_checkpoint() -> Optional[str]:
    """The checkpoint handed to this trial (PBT warm start), if any."""
    session = get_session()
    if session is None:
        return None
    return getattr(session, "start_checkpoint", None)
