"""RemoteFunction: the @remote task handle.

Counterpart of the reference's RemoteFunction (reference:
python/ray/remote_function.py:266 _remote) with the same .remote()/.options()
surface.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ray_option_utils import (
    TASK_DEFAULTS,
    merge_options,
    resources_from_options,
    strategy_from_options,
)


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._default_options = merge_options(TASK_DEFAULTS, options)
        functools.update_wrapper(self, fn)
        self._precompute()

    def _precompute(self):
        # Options are immutable per handle: derive the per-call submit
        # arguments once instead of on every `.remote()` (hot path).
        opts = self._default_options
        self._resources = resources_from_options(opts)
        self._strategy = strategy_from_options(opts)
        self._call_name = opts["name"] or self._function.__qualname__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__!r} cannot be called directly; "
            f"use {self._function.__name__}.remote()")

    def options(self, **task_options) -> "RemoteFunction":
        new = RemoteFunction.__new__(RemoteFunction)
        new._function = self._function
        new._default_options = merge_options(self._default_options, task_options)
        functools.update_wrapper(new, self._function)
        new._precompute()
        return new

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: dag_node bind API); execute via
        node.execute() or run durably via ray_tpu.workflow.run()."""
        from ray_tpu.dag import DAGNode

        return DAGNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        opts = self._default_options
        core = worker_mod.require_core()
        num_returns = opts["num_returns"]
        stream = False
        if num_returns == "streaming":
            # streaming generators: dynamic packing, but every yielded item
            # is forced into plasma at yield time so the caller can consume
            # refs WHILE the task still runs (ObjectRefGenerator.stream)
            num_returns, stream = -1, True
        if num_returns == "dynamic":
            # dynamic generators (reference: num_returns="dynamic" —
            # ObjectRefGenerator whose refs materialize when the task ends)
            num_returns = -1
        refs = core.submit_task(
            self._function,
            args,
            kwargs,
            name=self._call_name,
            num_returns=num_returns,
            resources=dict(self._resources),
            strategy=self._strategy,
            max_retries=opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            runtime_env=opts["runtime_env"],
            stream_returns=stream,
        )
        if num_returns == -1:
            from ray_tpu._private.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0], streaming=stream)
        if num_returns == 1:
            return refs[0]
        return refs

    @property
    def func(self):
        """The underlying Python function (reference exposes __wrapped__)."""
        return self._function
