"""Device mesh construction for dp/fsdp/tp/sp/ep axes — including hybrid
ICI x DCN meshes spanning multiple TPU slices.

TPU-native core: a ``jax.sharding.Mesh`` over all global devices, with ICI-
friendly axis ordering (innermost axes map to physically-adjacent chips so tp/sp
collectives ride the fastest links — `jax.experimental.mesh_utils` handles the
physical layout).

Multi-slice (SURVEY §5.8): ``MeshConfig(dcn_dp=..., dcn_pp=...)`` builds a
hybrid mesh where ONLY the dp and pp axes cross slice boundaries — gradient
all-reduce and pipeline stage hand-offs are the traffic patterns that
amortize DCN latency (one transfer per step), while tp/sp/ep collectives
stay strictly inside a slice's ICI.  This is the mesh recipe of
``mesh_utils.create_hybrid_device_mesh`` (and the scaling-book's
"data-parallel across slices, model-parallel within" rule); on hardware the
slice boundary is discovered from device attributes, and on the virtual CPU
platform contiguous device blocks stand in for slices so the sharding
compiles + executes in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXES = ("pp", "dp", "fsdp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes per logical axis; -1 on at most one axis means 'absorb the rest'.

    ``dcn_dp``/``dcn_pp`` extend the dp/pp axes ACROSS slices over DCN: the
    final logical axis size is ``dcn_axis * ici_axis`` with the DCN factor
    major, so neighboring positions along dp/pp stay within a slice and only
    the outermost hop crosses slices."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    dcn_dp: int = 1
    dcn_pp: int = 1

    @property
    def n_slices(self) -> int:
        return self.dcn_dp * self.dcn_pp

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """ICI (per-slice) sizes; wildcards absorb per-slice devices."""
        if n_devices % self.n_slices:
            raise ValueError(
                f"{n_devices} devices not divisible into {self.n_slices} "
                f"slices (dcn_dp={self.dcn_dp}, dcn_pp={self.dcn_pp})")
        per_slice = n_devices // self.n_slices
        sizes = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                 "tp": self.tp, "sp": self.sp, "ep": self.ep}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if wild:
            if per_slice % fixed:
                raise ValueError(
                    f"{per_slice} per-slice devices not divisible by fixed "
                    f"axes product {fixed}")
            sizes[wild[0]] = per_slice // fixed
        total = int(np.prod(list(sizes.values())))
        if total != per_slice:
            raise ValueError(
                f"mesh {sizes} covers {total} devices but {per_slice} are "
                f"present per slice")
        return sizes


def mesh_shape_for(n_devices: int, tp: int = 1, sp: int = 1, fsdp: int = 1,
                   ep: int = 1) -> Dict[str, int]:
    return MeshConfig(dp=-1, fsdp=fsdp, tp=tp, sp=sp, ep=ep).resolve(n_devices)


def _group_by_slice(devices, n_slices: int):
    """Partition devices into slices: by the hardware's slice index when the
    platform exposes one, else contiguous equal blocks (virtual platforms)."""
    by_idx: Dict[int, list] = {}
    for d in devices:
        idx = getattr(d, "slice_index", None)
        if idx is None:
            break
        by_idx.setdefault(idx, []).append(d)
    else:
        if len(by_idx) == n_slices:
            return [by_idx[k] for k in sorted(by_idx)]
        if len(by_idx) % n_slices == 0 and len(by_idx) > n_slices:
            # more physical slices than DCN groups: fold evenly
            keys = sorted(by_idx)
            per = len(keys) // n_slices
            return [sum((by_idx[k] for k in keys[i * per:(i + 1) * per]), [])
                    for i in range(n_slices)]
    per = len(devices) // n_slices
    return [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]


def build_mesh(config: Optional[MeshConfig] = None, devices=None):
    """Build a Mesh over the given (default: all global) devices.

    Axis order is (pp, dp, fsdp, sp, tp, ep) outer→inner: tp/ep innermost so
    their all-to-all/all-gather traffic lands on the closest ICI neighbors.
    With ``dcn_dp``/``dcn_pp`` > 1 the mesh is hybrid: per-slice ICI meshes
    stacked so dp/pp get a DCN-major extra factor while every other axis
    stays inside one slice.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    order = ("pp", "dp", "fsdp", "sp", "tp", "ep")
    ici_shape = tuple(sizes[a] for a in order)

    def slice_mesh(devs):
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(ici_shape, devices=devs)
        except Exception:
            return np.asarray(devs).reshape(ici_shape)

    if config.n_slices == 1:
        return Mesh(slice_mesh(devices), order)

    # hybrid: stack per-slice meshes as (dcn_pp, dcn_dp, *ici_shape), then
    # merge the DCN factors into the pp/dp dims (DCN-major)
    groups = _group_by_slice(devices, config.n_slices)
    stack = np.stack([slice_mesh(g) for g in groups])
    stack = stack.reshape((config.dcn_pp, config.dcn_dp) + ici_shape)
    # (dcn_pp, dcn_dp, pp, dp, fsdp, sp, tp, ep)
    #   -> (dcn_pp, pp, dcn_dp, dp, fsdp, sp, tp, ep) -> merge pairs
    stack = np.transpose(stack, (0, 2, 1, 3, 4, 5, 6, 7))
    final_shape = (config.dcn_pp * sizes["pp"], config.dcn_dp * sizes["dp"]) \
        + ici_shape[2:]
    return Mesh(stack.reshape(final_shape), order)


def local_mesh(axis: str = "dp"):
    """A 1-axis mesh over this process's addressable devices (single-host DP)."""
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.local_devices())
    return Mesh(devs, (axis,))
