"""Device mesh construction for dp/fsdp/tp/sp/ep axes.

TPU-native core: a ``jax.sharding.Mesh`` over all global devices, with ICI-
friendly axis ordering (innermost axes map to physically-adjacent chips so tp/sp
collectives ride the fastest links — `jax.experimental.mesh_utils` handles the
physical layout).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXES = ("pp", "dp", "fsdp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes per logical axis; -1 on at most one axis means 'absorb the rest'."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                 "tp": self.tp, "sp": self.sp, "ep": self.ep}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} covers {total} devices but {n_devices} are present")
        return sizes


def mesh_shape_for(n_devices: int, tp: int = 1, sp: int = 1, fsdp: int = 1,
                   ep: int = 1) -> Dict[str, int]:
    return MeshConfig(dp=-1, fsdp=fsdp, tp=tp, sp=sp, ep=ep).resolve(n_devices)


def build_mesh(config: Optional[MeshConfig] = None, devices=None):
    """Build a Mesh over the given (default: all global) devices.

    Axis order is (dp, fsdp, sp, tp, ep) outer→inner: tp/ep innermost so their
    all-to-all/all-gather traffic lands on the closest ICI neighbors.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    # pp outermost: stage boundaries tolerate the slowest links (DCN between
    # slices); tp/ep innermost for the tightest ICI neighborhoods.
    order = ("pp", "dp", "fsdp", "sp", "tp", "ep")
    shape = tuple(sizes[a] for a in order)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, order)


def local_mesh(axis: str = "dp"):
    """A 1-axis mesh over this process's addressable devices (single-host DP)."""
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.local_devices())
    return Mesh(devs, (axis,))
