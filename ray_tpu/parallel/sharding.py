"""Partition rules: map parameter names to PartitionSpecs over the mesh.

Regex-rule matching in the t5x/EasyLM style (public pattern; see SNIPPETS.md [3]
for the shape of the idea): each rule is (name_regex, PartitionSpec); the first
match wins; scalars are replicated.  This is the TP/FSDP machinery the reference
delegates to DeepSpeed/Accelerate (SURVEY §2.3 'TP: absent from Ray itself') —
here it is first-class and compiler-driven (GSPMD inserts the collectives).
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


def _spec(*axes):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*axes)


class PartitionRules:
    def __init__(self, rules: Sequence[Tuple[str, Any]]):
        self.rules = list(rules)

    def spec_for(self, path: str, shape: Tuple[int, ...]):
        from jax.sharding import PartitionSpec

        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return PartitionSpec()
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                return spec
        return PartitionSpec()  # replicate by default


def gpt_partition_rules() -> PartitionRules:
    """Megatron-style TP + FSDP sharding for the GPT family (ray_tpu.models.gpt2).

    Weight matrices split on 'tp'; the remaining big dimension is sharded over
    'fsdp' so parameters also scale with the fsdp axis (ZeRO-3-like).  XLA turns
    these into all-gather on use + reduce-scatter on grad, over ICI.
    """
    return PartitionRules([
        # embeddings: (vocab, embed) — vocab on tp, embed on fsdp
        (r"wte/embedding", _spec("tp", "fsdp")),
        (r"wpe/embedding", _spec(None, "fsdp")),
        # attention qkv: (embed, heads*head_dim) — split heads over tp
        (r"attn/(q|k|v|qkv)_proj/kernel", _spec("fsdp", "tp")),
        (r"attn/out_proj/kernel", _spec("tp", "fsdp")),
        # mlp: (embed, 4*embed) in, (4*embed, embed) out
        (r"mlp/fc_in/kernel", _spec("fsdp", "tp")),
        (r"mlp/fc_out/kernel", _spec("tp", "fsdp")),
        # biases/layernorms replicated
        (r"bias|scale|ln", _spec()),
        # lm head (embed, vocab)
        (r"lm_head/kernel", _spec("fsdp", "tp")),
        # MoE experts: leading expert dim over ep (models/moe.py)
        (r"router/kernel", _spec()),
        (r"moe_mlp/w_in", _spec("ep", "fsdp", "tp")),
        (r"moe_mlp/w_out", _spec("ep", "tp", "fsdp")),
    ])


def llama_partition_rules() -> PartitionRules:
    """Megatron-style TP + FSDP sharding for the Llama family
    (ray_tpu.models.llama): same recipe as gpt_partition_rules, names
    matched to the RoPE/RMSNorm/SwiGLU module layout."""
    return PartitionRules([
        (r"wte/embedding", _spec("tp", "fsdp")),
        (r"attn/(wq|wk|wv)/kernel", _spec("fsdp", "tp")),
        (r"attn/wo/kernel", _spec("tp", "fsdp")),
        (r"mlp/(gate_proj|up_proj)/kernel", _spec("fsdp", "tp")),
        (r"mlp/down_proj/kernel", _spec("tp", "fsdp")),
        (r"norm|scale", _spec()),
        (r"lm_head/kernel", _spec("fsdp", "tp")),
    ])


def _flatten_with_paths(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
            for k in path)
        out.append((name, leaf))
    return out, treedef


def match_partition_rules(rules, params):
    """Pytree of params → pytree of PartitionSpec.  ``rules`` is a
    PartitionRules or a raw ``[(regex, PartitionSpec), ...]`` sequence."""
    import jax

    if not isinstance(rules, PartitionRules):
        rules = PartitionRules(rules)
    flat, treedef = _flatten_with_paths(params)
    specs = [rules.spec_for(name, getattr(leaf, "shape", ())) for name, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def host_to_global(x, sharding):
    """Host value -> global jax.Array under ``sharding``.

    Single-process meshes take the plain ``device_put`` path.  When the
    sharding spans processes, ``device_put`` of a host value is not a
    supported multi-controller transfer (on the CPU/gloo backend it issues
    mismatched point-to-point ops that abort the whole gang); the supported
    construction is per-process assembly from addressable shards.  Every
    caller here holds the SAME full host value on every process (seeded init,
    seeded batches), so each process can slice its own shards locally and no
    bytes cross the wire.
    """
    import jax

    if all(d.process_index == jax.process_index()
           for d in sharding.device_set):
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def shard_pytree(params, specs, mesh):
    """Device-put a pytree with NamedShardings built from specs (multi-
    process safe: see host_to_global)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda x, s: host_to_global(x, NamedSharding(mesh, s)), params, specs)


def with_sharding_constraint(x, spec, mesh=None):
    """Annotate an intermediate value's sharding (inside jit)."""
    import jax
    from jax.sharding import NamedSharding

    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)
