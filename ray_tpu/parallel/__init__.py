"""Parallelism layer: device meshes, sharding rules, distributed init.

The TPU-native replacement for the reference's process-group/NCCL plumbing
(reference: python/ray/train/torch/config.py:66,116 _setup_torch_process_group;
torch-xla precedent train/torch/xla/config.py:20).  Here parallelism is
declarative: pick a mesh, annotate shardings, let XLA insert collectives over
ICI (GSPMD), following the mesh/axis conventions of the scaling playbook:

- ``dp``   data parallelism (pure replication of params, sharded batch)
- ``fsdp`` fully-sharded data parallelism (params sharded over this axis too)
- ``tp``   tensor parallelism (weight matrices split; activations all-gathered/
           reduce-scattered by XLA)
- ``sp``   sequence/context parallelism (long-context: ring attention over this
           axis — absent from the reference entirely, SURVEY §5.7)
- ``ep``   expert parallelism (MoE all-to-all)
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    local_mesh,
    mesh_shape_for,
)
from ray_tpu.parallel.sharding import (
    PartitionRules,
    gpt_partition_rules,
    match_partition_rules,
    shard_pytree,
    with_sharding_constraint,
)

__all__ = [
    "MeshConfig", "build_mesh", "local_mesh", "mesh_shape_for",
    "PartitionRules", "gpt_partition_rules", "match_partition_rules",
    "shard_pytree", "with_sharding_constraint",
]
