"""Pipeline parallelism: SPMD GPipe over a ``pp`` mesh axis.

The reference has no pipeline engine in core (SURVEY §2.3: PP "absent from
core"; compiled DAGs + NCCL channels are the intended substrate).  The
TPU-native equivalent needs no channel runtime at all: every pp rank runs the
SAME program under ``shard_map``; stage weights live sharded on ``pp``;
activations rotate ranks with ``jax.lax.ppermute`` over ICI each step of a
``fori_loop`` schedule.  XLA sees one static program — the "pipeline" is just
a rolled loop with neighbor permutes (the scaling-book recipe).

Schedule: classic GPipe fill-drain.  M microbatches, S stages,
T = M + S - 1 ticks; rank 0 ingests microbatch t at tick t; rank S-1 emits
microbatch t-(S-1).  Bubble fraction (S-1)/T, amortized by more microbatches.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches,
                   mesh, axis: str = "pp"):
    """Run ``stage_fn(params, x)`` as an S-stage pipeline.

    Args:
      stage_fn: one pipeline stage; same signature on every rank.
      stacked_params: pytree with leading dim S, sharded over ``axis``.
      microbatches: (M, ...) array of microbatch inputs (replicated).
      mesh: jax Mesh containing ``axis``.
    Returns: (M, ...) outputs of the final stage (replicated).
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    M = microbatches.shape[0]
    T = M + S - 1
    fwd = [(i, (i + 1) % S) for i in range(S)]

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    def _smap_variants(fn):
        # Partial-manual shard_map (jax >= 0.8/0.9): ONLY the pp axis is
        # manual, so dp/fsdp/tp/sp shardings of the activations stay under
        # GSPMD and compose with the pipeline untouched.  Partial-manual is
        # rejected outside jit (and by older jax), so a full-manual variant
        # follows — correct when the other mesh axes carry no sharding.
        try:
            yield shard_map(fn, mesh=mesh, in_specs=(param_specs, P()),
                            out_specs=P(), check_vma=False,
                            axis_names={axis})
        except TypeError:
            pass
        try:
            yield shard_map(fn, mesh=mesh, in_specs=(param_specs, P()),
                            out_specs=P(), check_vma=False)
        except TypeError:
            yield shard_map(fn, mesh=mesh, in_specs=(param_specs, P()),
                            out_specs=P(), check_rep=False)

    def run(params_local, xs):
        rank = jax.lax.axis_index(axis)
        stage_p = jax.tree_util.tree_map(lambda a: a[0], params_local)

        def tick(t, carry):
            buf, outs = carry
            # rank 0 ingests microbatch t; downstream ranks consume what
            # arrived over ICI last tick.  Clip keeps the gather in-bounds
            # during the drain phase (values unused then).
            ingest = xs[jnp.clip(t, 0, M - 1)]
            x_in = jnp.where(rank == 0, ingest, buf)
            y = stage_fn(stage_p, x_in)
            # final stage writes microbatch t-(S-1) once it's real
            mb = t - (S - 1)
            is_out = jnp.logical_and(rank == S - 1, mb >= 0)
            outs = jnp.where(
                is_out,
                outs.at[jnp.clip(mb, 0, M - 1)].set(y),
                outs)
            buf = jax.lax.ppermute(y, axis, fwd)
            return buf, outs

        buf0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        _, outs = jax.lax.fori_loop(0, T, tick, (buf0, outs0))
        # outs is populated only on the last rank; psum over the (otherwise
        # zero) copies replicates it without a separate broadcast.
        return jax.lax.psum(outs, axis)

    err = None
    for mapped in _smap_variants(run):
        try:
            return mapped(stacked_params, microbatches)
        except ValueError as e:
            # partial-manual rejected (e.g. eager call outside jit): try the
            # full-manual variant
            err = e
            continue
    raise err
